"""Memory-mapped embedding inventory — the query plane's read substrate.

The batch plane (daemon/engine) produces embeddings; this module serves
them at interactive rates without ever holding a full ``[G, H]`` table
per query. A *bundle* is the generational directory written by
``io/writers.write_inventory_bundle``::

    <root>/<key>/
        GENERATION       pointer: one line naming the live generation
        gen-NNNNNN/
            embeddings.npy   float32 [G, H]
            norms.npy        float32 [G] precomputed row L2 norms
            scores.npy       float32 [2, G] prognostic scores (optional)
            genes.txt        one symbol per row, row order == array order
            meta.json        lane/run metadata (job_id, variant, config)
            MANIFEST.json    sha256 + byte size per file (utils/integrity)

A reader resolves the pointer ONCE at map time and reads every file
from that generation, so a concurrent republish (the ``update`` op's
atomic flip — writers.py renames the pointer last) can never hand it a
mixed old/new file set. Bundles from before the generational layout
keep their files flat in ``<key>/`` (no pointer) and map unchanged.

The daemon publishes one bundle per completed (job, variant) under
``<state>/inventory/<job_id>/<variant>/``; solo runs with
``--emit-inventory`` publish ``<result_name>_inventory/``. Both go
through the same writer, so the array files are byte-identical twins.

:class:`InventoryCatalog` rebuilds its view of the world from disk on
every listing (boot needs no replay — the bundles ARE the catalog) and
lazily memory-maps bundles behind a byte-budgeted LRU: ``np.load(...,
mmap_mode='r')`` maps the arrays without copying, the cold-path
manifest verification is the only full read a bundle ever gets, and
queries touch O(block) pages via the blocked kernels in ``ops/knn.py``.
A tampered or torn bundle raises :class:`InventoryError` with a
structured code instead of serving corrupt rows.

This module is deliberately **jax-free** (numpy + stdlib only): the
router imports it for its failover read path, and the router must boot
on accelerator-free hosts.
"""
from __future__ import annotations

import collections
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from g2vec_tpu.io.writers import (GENERATION_FILE, INVENTORY_MANIFEST,
                                  read_generation)
from g2vec_tpu.ops import ann as ann_ops
from g2vec_tpu.ops import knn

#: Manifest-name prefixes on the LENIENT verification tier: derived
#: data whose corruption may cost coverage (index probes, biomarker
#: shortlists, delta fingerprints) but never correctness — the exact
#: arrays stay strict.
LENIENT_PREFIXES = ("ann_", "delta_")

#: Sub-ops a ``query`` request may name (protocol vocabulary; the CLI
#: and daemon/router dispatch validate against this tuple).
QUERY_SUBOPS = ("neighbors", "topk_biomarkers", "meta", "list")

#: Retrieval modes for the ``neighbors`` sub-op: ``approx`` probes the
#: bundle's IVF index (ops/ann.py) and exact-rescores the survivors —
#: float-exact whenever the true top-k lives in the probed lists —
#: while ``exact`` is the ground-truth blocked kernel. ``approx`` is
#: the default and silently serves exactly when a bundle has no index
#: (small bundles below the auto threshold, pre-index republications).
QUERY_MODES = ("approx", "exact")

#: Federated cross-bundle sub-ops (the ``fquery`` op): ``gene_rank``
#: asks every bundle where it ranks ``gene`` in its prognostic scores;
#: ``bundle_overlap`` ranks bundles by how much their neighborhood of
#: ``gene`` overlaps a reference neighbor set.
FQUERY_SUBOPS = ("gene_rank", "bundle_overlap")


class InventoryError(Exception):
    """A structured query-plane failure: ``code`` is wire-stable
    (``not_found`` / ``torn`` / ``tampered`` / ``bad_query`` /
    ``scores_unavailable``), ``detail`` is for humans."""

    def __init__(self, code: str, detail: str):
        super().__init__(f"{code}: {detail}")
        self.code = code
        self.detail = detail


class _Bundle:
    """One mapped bundle: mmap'd arrays + the eager gene index.

    Immutable after construction — the catalog lock only guards the
    LRU bookkeeping, never per-bundle state.
    """

    def __init__(self, path: str):
        # Resolve the generation pointer ONCE; every file below reads
        # from the resolved root, so a republish flipping the pointer
        # mid-map cannot hand this bundle a mixed file set.
        generation = read_generation(path)
        if generation and (not generation.startswith("gen-")
                           or "/" in generation or ".." in generation):
            raise InventoryError(
                "torn", f"{path}: corrupt {GENERATION_FILE} pointer "
                        f"({generation!r})")
        root = os.path.join(path, generation) if generation else path
        man_path = os.path.join(root, INVENTORY_MANIFEST)
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise InventoryError(
                "torn", f"{root}: no {INVENTORY_MANIFEST} (interrupted "
                f"publication or not a bundle)")
        except ValueError as e:
            raise InventoryError("torn", f"{man_path}: unparseable ({e})")
        from g2vec_tpu.utils.integrity import sha256_file

        files = manifest.get("files", {})
        # Verification is two-tier: the EXACT arrays are load-bearing
        # (a mismatch refuses the whole bundle, as ever), while the
        # ``ann_*`` index files degrade — a torn/tampered index is
        # refused AT MAP TIME with a structured warning and the bundle
        # still serves through the exact path. A corrupted index can
        # therefore never change an answer, only slow one down.
        bad: Dict[str, dict] = {}
        for name, want in sorted(files.items()):
            fp = os.path.join(root, name)
            lenient = name.startswith(LENIENT_PREFIXES)
            if not os.path.exists(fp):
                if lenient:
                    bad.setdefault(name.split("_", 1)[0], {
                        "code": "torn",
                        "detail": f"{root}: manifest names {name} but "
                                  f"it is missing"})
                    continue
                raise InventoryError("torn", f"{root}: manifest names "
                                             f"{name} but it is missing")
            if os.path.getsize(fp) != want.get("bytes"):
                if lenient:
                    bad.setdefault(name.split("_", 1)[0], {
                        "code": "tampered",
                        "detail": f"{fp}: {os.path.getsize(fp)} bytes, "
                                  f"manifest says {want.get('bytes')}"})
                    continue
                raise InventoryError(
                    "tampered", f"{fp}: {os.path.getsize(fp)} bytes, "
                                f"manifest says {want.get('bytes')}")
            if sha256_file(fp) != want.get("sha256"):
                if lenient:
                    bad.setdefault(name.split("_", 1)[0], {
                        "code": "tampered",
                        "detail": f"{fp}: sha256 mismatch vs manifest"})
                    continue
                raise InventoryError("tampered", f"{fp}: sha256 mismatch "
                                                 f"vs manifest")
        ann_bad = bad.get("ann")
        for required in ("embeddings.npy", "norms.npy", "genes.txt",
                         "meta.json"):
            if required not in files:
                raise InventoryError(
                    "torn", f"{root}: manifest lacks {required}")
        self.path = path
        self.root = root
        #: The live generation name mapped at construction ("" for a
        #: pre-generational flat bundle). Part of the QueryCache key,
        #: so a republish structurally invalidates cached answers.
        self.generation = generation
        self.embeddings = np.load(os.path.join(root, "embeddings.npy"),
                                  mmap_mode="r", allow_pickle=False)
        self.norms = np.load(os.path.join(root, "norms.npy"),
                             mmap_mode="r", allow_pickle=False)
        self.scores = None
        if "scores.npy" in files:
            self.scores = np.load(os.path.join(root, "scores.npy"),
                                  mmap_mode="r", allow_pickle=False)
        with open(os.path.join(root, "genes.txt")) as f:
            self.genes: List[str] = [ln.rstrip("\n") for ln in f]
        with open(os.path.join(root, "meta.json")) as f:
            self.meta = json.load(f)
        if self.embeddings.ndim != 2 or \
                self.embeddings.shape[0] != len(self.genes):
            raise InventoryError(
                "tampered", f"{root}: embeddings {self.embeddings.shape} "
                            f"vs {len(self.genes)} genes")
        self.gene_index: Dict[str, int] = {
            g: i for i, g in enumerate(self.genes)}
        #: IVF index (ops/ann.py), or None with ``ann_error`` carrying
        #: the structured refusal when index files exist but failed
        #: verification or shape sanity. Both None = bundle simply has
        #: no index (below the auto threshold, or ann disabled).
        self.ann = None
        self.ann_error: Optional[dict] = None
        #: int64 [2, M] exact-prefix biomarker shortlist (ann_scores.npy)
        #: or None; serves approx ``topk_biomarkers`` for k <= M with
        #: answers identical to the exact kernel by construction.
        self.ann_scores = None
        ann_names = [n for n in files if n.startswith("ann_")]
        if ann_bad is not None:
            self.ann_error = ann_bad
        elif ann_names:
            try:
                missing = [n for n in ann_ops.ANN_FILES
                           if n not in files]
                if missing:
                    raise ValueError(f"manifest lacks {missing}")
                pvecs = None
                if "ann_vectors.npy" in files:
                    pvecs = np.load(
                        os.path.join(root, "ann_vectors.npy"),
                        mmap_mode="r", allow_pickle=False)
                self.ann = ann_ops.IVFIndex(
                    np.load(os.path.join(root, "ann_centroids.npy"),
                            mmap_mode="r", allow_pickle=False),
                    np.load(os.path.join(root, "ann_postings.npy"),
                            mmap_mode="r", allow_pickle=False),
                    np.load(os.path.join(root, "ann_offsets.npy"),
                            mmap_mode="r", allow_pickle=False),
                    n_rows=len(self.genes),
                    hidden=int(self.embeddings.shape[1]),
                    pvecs=pvecs)
                if "ann_scores.npy" in files and self.scores is not None:
                    short = np.load(
                        os.path.join(root, "ann_scores.npy"),
                        mmap_mode="r", allow_pickle=False)
                    if short.ndim != 2 \
                            or short.shape[0] != self.scores.shape[0] \
                            or short.shape[1] > len(self.genes):
                        raise ValueError(
                            f"ann_scores {short.shape} vs "
                            f"[{self.scores.shape[0]}, "
                            f"<= {len(self.genes)}]")
                    self.ann_scores = short
            except (OSError, ValueError) as e:
                self.ann = None
                self.ann_scores = None
                self.ann_error = {
                    "code": "tampered",
                    "detail": f"{root}: ann index refused ({e})"}
        #: delta_fingerprints.json payload for the update plane's
        #: owner-range diff, or None (absent / failed the lenient
        #: verification tier — incrementality degrades to a full
        #: re-walk, never a wrong answer).
        self.fingerprints = None
        if "delta_fingerprints.json" in files and "delta" not in bad:
            try:
                with open(os.path.join(
                        root, "delta_fingerprints.json")) as f:
                    self.fingerprints = json.load(f)
            except (OSError, ValueError):
                self.fingerprints = None
        #: mapped-budget charge: the npy payloads (the mmap'd set).
        self.nbytes = sum(int(w.get("bytes", 0))
                          for n, w in files.items() if n.endswith(".npy"))


def _is_bundle(path: str) -> bool:
    """A directory is a bundle if it carries a generation pointer
    (generational layout) or a root manifest (pre-generational flat
    layout)."""
    return os.path.exists(os.path.join(path, GENERATION_FILE)) or \
        os.path.exists(os.path.join(path, INVENTORY_MANIFEST))


def scan_bundles(roots: Sequence[str]) -> Dict[str, str]:
    """key -> bundle dir, rebuilt from disk (depth <= 2 under each root:
    ``<job_id>/<variant>/`` for served bundles, ``<name>_inventory/``
    for solo ones). First root wins on key collision."""
    found: Dict[str, str] = {}
    for root in roots:
        if not os.path.isdir(root):
            continue
        for d1 in sorted(os.listdir(root)):
            p1 = os.path.join(root, d1)
            if not os.path.isdir(p1) or d1.startswith("."):
                continue
            if _is_bundle(p1):
                found.setdefault(d1, p1)
                continue
            for d2 in sorted(os.listdir(p1)):
                p2 = os.path.join(p1, d2)
                if os.path.isdir(p2) and not d2.startswith(".") and \
                        not d2.startswith("gen-") and _is_bundle(p2):
                    found.setdefault(f"{d1}/{d2}", p2)
    return found


def resolve_bundle_key(known: Dict[str, str], job_id: str, variant) \
        -> Tuple[Optional[str], Optional[dict]]:
    """Map (job_id, variant?) onto one key of ``known`` (a
    :func:`scan_bundles` result), or a structured error event. A
    depth-1 key (a solo ``--emit-inventory`` bundle) matches ``job_id``
    directly; served bundles live at ``<job_id>/<variant>`` and an
    omitted variant resolves only when the job has exactly one. Shared
    by the daemon and the router so both address bundles identically."""
    if variant:
        key = f"{job_id}/{variant}"
        if key in known:
            return key, None
        return None, {
            "event": "error", "error": "not_found",
            "job_id": job_id, "detail": f"no bundle {key!r}",
            "variants": sorted(k.split("/", 1)[1] for k in known
                               if k.startswith(job_id + "/"))}
    if job_id in known:
        return job_id, None
    cands = sorted(k for k in known if k.startswith(job_id + "/"))
    if len(cands) == 1:
        return cands[0], None
    if not cands:
        return None, {"event": "error", "error": "not_found",
                      "job_id": job_id,
                      "detail": f"no bundle for job {job_id!r}"}
    return None, {
        "event": "error", "error": "ambiguous_variant",
        "job_id": job_id,
        "detail": "job has several variants; pass 'variant'",
        "variants": [c.split("/", 1)[1] for c in cands]}


class InventoryCatalog:
    """Byte-budgeted LRU of memory-mapped bundles over N disk roots.

    ``get`` maps lazily (cold path pays one manifest verification —
    the only full read) and evicts least-recently-used bundles until
    the mapped set fits ``budget_bytes`` again. All LRU state is
    guarded by one lock; the load itself also runs under it, which
    serializes cold maps — acceptable because the warm path is a dict
    hit and the bench pins cold-vs-warm separately.
    """

    def __init__(self, roots: Sequence[str], budget_bytes: int):
        self.roots = [os.path.abspath(r) for r in roots]
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        #: key -> _Bundle in LRU order (last = most recent).
        # guarded-by: _lock
        self._mapped: "collections.OrderedDict[str, _Bundle]" = \
            collections.OrderedDict()
        self._bytes_mapped = 0      # guarded-by: _lock
        self._evictions = 0         # guarded-by: _lock
        self._map_errors = 0        # guarded-by: _lock
        self._cold_maps = 0         # guarded-by: _lock

    def get(self, key: str) -> _Bundle:
        with self._lock:
            hit = self._mapped.get(key)
            if hit is not None:
                self._mapped.move_to_end(key)
                return hit
            path = scan_bundles(self.roots).get(key)
            if path is None:
                raise InventoryError(
                    "not_found", f"no bundle {key!r} under "
                                 f"{self.roots} (known: "
                                 f"{sorted(scan_bundles(self.roots))[:8]})")
            try:
                bundle = _Bundle(path)
            except InventoryError:
                self._map_errors += 1
                raise
            self._mapped[key] = bundle
            self._bytes_mapped += bundle.nbytes
            self._cold_maps += 1
            while self._bytes_mapped > self.budget_bytes and \
                    len(self._mapped) > 1:
                _, old = self._mapped.popitem(last=False)
                self._bytes_mapped -= old.nbytes
                self._evictions += 1
            return bundle

    def invalidate(self, key: str) -> None:
        with self._lock:
            old = self._mapped.pop(key, None)
            if old is not None:
                self._bytes_mapped -= old.nbytes

    def generation(self, key: str) -> str:
        """The generation the next :func:`run_query` over ``key`` will
        answer from: the already-mapped bundle's pointer when cached —
        the cached arrays ARE the answer source, and keying the
        QueryCache by the on-disk pointer instead could label an
        old-array answer with the new generation inside the
        flip→invalidate window — else the on-disk pointer. Unknown or
        flat bundles read as ``""`` (their queries fail or key
        generation-lessly, both safe)."""
        with self._lock:
            hit = self._mapped.get(key)
            if hit is not None:
                return hit.generation
        path = scan_bundles(self.roots).get(key)
        return read_generation(path) if path else ""

    def listing(self) -> List[dict]:
        """Catalog view straight from disk (cheap: meta.json only,
        nothing is mapped or verified)."""
        out = []
        for key, path in sorted(scan_bundles(self.roots).items()):
            entry = {"bundle": key}
            gen = read_generation(path)
            try:
                with open(os.path.join(path, gen, "meta.json")) as f:
                    meta = json.load(f)
                entry.update(
                    n_genes=meta.get("n_genes"), hidden=meta.get("hidden"),
                    has_scores=meta.get("has_scores"),
                    ann=bool(meta.get("ann")),
                    generation=gen or None)
            except (OSError, ValueError):
                entry["torn"] = True
            out.append(entry)
        return out

    def stats(self) -> dict:
        cataloged = len(scan_bundles(self.roots))
        with self._lock:
            return {"bundles_cataloged": cataloged,
                    "bundles_mapped": len(self._mapped),
                    "bytes_mapped": self._bytes_mapped,
                    "budget_bytes": self.budget_bytes,
                    "cold_maps": self._cold_maps,
                    "evictions": self._evictions,
                    "map_errors": self._map_errors}


class QueryCache:
    """Small keyed LRU over fully-rendered query results.

    Keys are ``(bundle, sub-op, args)`` strings; values are the exact
    JSON-able response dicts. Entry-count bounded (results are tiny:
    k genes + k floats), byte budgets stay the catalog's concern.
    """

    def __init__(self, capacity: int = 128):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        #: key -> response dict, LRU order.
        # guarded-by: _lock
        self._entries: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._hits = 0      # guarded-by: _lock
        self._misses = 0    # guarded-by: _lock

    def get_or_put(self, key: str, compute) -> Tuple[dict, bool]:
        """One critical section around lookup+insert would hold the
        lock across ``compute`` (a blocked matmul), so this is
        deliberately lookup -> compute -> insert; two racing misses
        both compute and the second insert wins — idempotent, queries
        are pure reads."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self._hits += 1
                return hit, True
            self._misses += 1
        value = compute()
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return value, False

    def invalidate_bundle(self, bundle_key: str) -> None:
        """Drop every cached result for one bundle (republication)."""
        with self._lock:
            for k in [k for k in self._entries
                      if k.startswith(bundle_key + "\x00")]:
                del self._entries[k]

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {"entries": len(self._entries),
                    "capacity": self.capacity,
                    "hits": self._hits, "misses": self._misses,
                    "hit_rate": round(self._hits / total, 4)
                    if total else None}


def cache_key(bundle: str, q: str, gene: Optional[str], k: int,
              mode: str = "exact", nprobe: int = 0,
              generation: str = "") -> str:
    """The QueryCache key. ``mode``/``nprobe`` are part of it so an
    approx result can never be served for an exact request (or for a
    different probe width) of the same (bundle, q, gene, k).
    ``generation`` is the bundle's live generation pointer, read at
    request time: a republish flips the pointer, which changes every
    key, so a cached pre-flip answer is STRUCTURALLY unreachable even
    if the explicit ``invalidate_bundle`` call were lost (pinned by
    tests/test_update.py)."""
    return "\x00".join((bundle, q, gene or "", str(int(k)),
                        mode, str(int(nprobe)), generation))


def run_query(catalog: InventoryCatalog, q: str, bundle_key: str,
              gene: Optional[str] = None, k: int = 10,
              block_rows: int = 8192, mode: str = "approx",
              nprobe: int = 0) -> dict:
    """Evaluate one ``neighbors`` / ``topk_biomarkers`` / ``meta``
    sub-op against the catalog (``list`` is :meth:`InventoryCatalog.
    listing` — it takes no bundle). Shared verbatim by the daemon and
    the router's failover read path so both answer identically.

    ``mode`` steers the ``neighbors`` sub-op only (the other sub-ops
    are always exact): ``approx`` probes the bundle's IVF index and
    exact-rescores survivors; ``exact`` is the ground-truth kernel.
    The response's ``recall_mode`` says how the answer was actually
    produced — ``approx``, ``exact``, or ``exact_fallback`` (an index
    was expected but refused at map time; ``ann_warning`` carries the
    structured refusal).
    """
    if q not in ("neighbors", "topk_biomarkers", "meta"):
        raise InventoryError("bad_query", f"unknown sub-op {q!r}; "
                                          f"expected one of {QUERY_SUBOPS}")
    if mode not in QUERY_MODES:
        raise InventoryError("bad_query", f"unknown mode {mode!r}; "
                                          f"expected one of {QUERY_MODES}")
    k = int(k)
    if q != "meta" and not (1 <= k <= 10000):
        raise InventoryError("bad_query", f"k must be in [1, 10000], "
                                          f"got {k}")
    nprobe = int(nprobe)
    if not (0 <= nprobe <= 10000):
        raise InventoryError("bad_query", f"nprobe must be in "
                                          f"[0, 10000], got {nprobe}")
    b = catalog.get(bundle_key)
    if q == "meta":
        return {"bundle": bundle_key, "meta": b.meta,
                "generation": b.generation,
                "mapped_bytes": b.nbytes, "n_genes": len(b.genes),
                "hidden": int(b.embeddings.shape[1])}
    if q == "neighbors":
        if not gene:
            raise InventoryError("bad_query",
                                 "neighbors needs a 'gene' symbol")
        gi = b.gene_index.get(gene)
        if gi is None:
            raise InventoryError("bad_query",
                                 f"gene {gene!r} not in bundle "
                                 f"{bundle_key!r}")
        qvec = np.asarray(b.embeddings[gi], dtype=np.float32)
        if mode == "approx" and b.ann is not None:
            eff = nprobe or ann_ops.DEFAULT_NPROBE
            idx, sims, ncand = ann_ops.ivf_topk(
                b.embeddings, b.norms, b.ann, qvec, k, nprobe=eff,
                exclude=gi, block_rows=block_rows)
            return {"bundle": bundle_key, "gene": gene, "k": k,
                    "generation": b.generation,
                    "neighbors": [b.genes[i] for i in idx],
                    "sims": [float(s) for s in sims],
                    "mode": "approx", "recall_mode": "approx",
                    "storage": "posting_major"
                    if b.ann.pvecs is not None else "gather",
                    "nprobe": int(min(max(eff, 1), b.ann.nlist)),
                    "nlist": b.ann.nlist, "candidates": ncand}
        idx, sims = knn.cosine_topk(b.embeddings, b.norms, qvec, k,
                                    exclude=gi, block_rows=block_rows)
        out = {"bundle": bundle_key, "gene": gene, "k": k,
               "generation": b.generation,
               "neighbors": [b.genes[i] for i in idx],
               "sims": [float(s) for s in sims],
               "mode": mode, "recall_mode": "exact"}
        if mode == "approx" and b.ann_error is not None:
            out["recall_mode"] = "exact_fallback"
            out["ann_warning"] = b.ann_error
        return out
    # topk_biomarkers
    if b.scores is None:
        raise InventoryError(
            "scores_unavailable",
            f"bundle {bundle_key!r} was republished from the durable "
            f"record's text outputs, which do not carry the [2, G] "
            f"score matrix — re-run the job to restore it")
    out = {"bundle": bundle_key, "k": k, "generation": b.generation}
    short = b.ann_scores
    if mode == "approx" and short is not None \
            and k <= int(short.shape[1]):
        # Shortlist prefix: ann_scores rows are the exact kernel's own
        # top-M order (computed at build time), and _topk_desc's
        # deterministic tie rule makes top-k a PREFIX of top-M — so
        # this answer is identical to the exact path, k row reads
        # instead of a [G] scan.
        out["recall_mode"] = "approx"
        out["shortlist_m"] = int(short.shape[1])
        for row, group in enumerate(("good", "poor")):
            idx = np.asarray(short[row, :k], dtype=np.int64)
            sc = np.asarray(b.scores[row], dtype=np.float32)[idx]
            out[group] = {"genes": [b.genes[i] for i in idx],
                          "scores": [float(s) for s in sc]}
        return out
    out["recall_mode"] = "exact"
    if mode == "approx" and b.ann_error is not None:
        out["recall_mode"] = "exact_fallback"
        out["ann_warning"] = b.ann_error
    for row, group in enumerate(("good", "poor")):
        idx, sc = knn.topk_scores(np.asarray(b.scores[row],
                                             dtype=np.float32), k)
        out[group] = {"genes": [b.genes[i] for i in idx],
                      "scores": [float(s) for s in sc]}
    return out


def run_fquery(catalog: InventoryCatalog, fq: str, gene: str,
               k: int = 50, mode: str = "approx", nprobe: int = 0,
               ref_genes: Optional[Sequence[str]] = None,
               block_rows: int = 8192) -> List[dict]:
    """Evaluate one federated sub-op against EVERY bundle the catalog
    can see, returning one partial dict per bundle — never aborting on
    a bad bundle (a torn/tampered/score-less bundle contributes a
    structured per-bundle ``error`` instead). The daemon runs this over
    its own inventory; the router runs it over a dead replica's shared
    state dir, so both produce merge-compatible partials.

    ``gene_rank``: per bundle, the 1-based rank of ``gene`` in each of
    the good/poor prognostic score rows (ties by ascending row index —
    the same order :func:`ops.knn.topk_scores` would surface them) and
    whether that lands in the top ``k``. ``bundle_overlap``: per bundle
    containing ``gene``, the fraction of ``ref_genes`` (the reference
    neighbor set) found in that bundle's own ``k`` nearest neighbors of
    ``gene`` — approx/exact per ``mode``, attributed via
    ``recall_mode``.
    """
    if fq not in FQUERY_SUBOPS:
        raise InventoryError(
            "bad_query", f"unknown fquery sub-op {fq!r}; expected one "
                         f"of {FQUERY_SUBOPS}")
    if not gene:
        raise InventoryError("bad_query", "fquery needs a 'gene' symbol")
    k = int(k)
    if not (1 <= k <= 10000):
        raise InventoryError("bad_query", f"k must be in [1, 10000], "
                                          f"got {k}")
    ref = None
    if fq == "bundle_overlap":
        if not ref_genes:
            raise InventoryError(
                "bad_query", "bundle_overlap needs 'ref_genes' (or a "
                             "reference 'job_id' the daemon/router "
                             "resolves into one)")
        ref = set(ref_genes)
    out: List[dict] = []
    for key in sorted(scan_bundles(catalog.roots)):
        part: dict = {"bundle": key}
        try:
            b = catalog.get(key)
        except InventoryError as e:
            part["error"] = e.code
            out.append(part)
            continue
        gi = b.gene_index.get(gene)
        if gi is None:
            part["present"] = False
            out.append(part)
            continue
        part["present"] = True
        if fq == "gene_rank":
            if b.scores is None:
                part["error"] = "scores_unavailable"
            else:
                for row, group in enumerate(("good", "poor")):
                    s = np.asarray(b.scores[row], dtype=np.float32)
                    sv = s[gi]
                    rank = int(1 + np.count_nonzero(s > sv)
                               + np.count_nonzero(s[:gi] == sv))
                    part[group] = {"rank": rank, "in_top_k": rank <= k}
        else:
            resp = run_query(catalog, "neighbors", key, gene=gene, k=k,
                             block_rows=block_rows, mode=mode,
                             nprobe=nprobe)
            shared = len(set(resp["neighbors"]) & ref)
            part["overlap"] = round(shared / max(len(ref), 1), 6)
            part["shared"] = shared
            part["recall_mode"] = resp.get("recall_mode", "exact")
        out.append(part)
    return out


def merge_fquery(fq: str, partials: Sequence[dict]) -> List[dict]:
    """Merge scatter-gathered per-bundle partials into one ranked list.

    Dedupe is first-wins by bundle key (callers put alive-owner answers
    before failover reads, so a live replica always outranks a disk
    read of the same bundle). Ordering: ``gene_rank`` sorts by best
    (lowest) rank across the good/poor groups; ``bundle_overlap`` by
    overlap descending; bundles without a score (absent gene,
    per-bundle errors) sort after scored ones; ties break by bundle
    key so the merged order is deterministic across runs.
    """
    seen: Dict[str, dict] = {}
    for p in partials:
        key = str(p.get("bundle"))
        if key not in seen:
            seen[key] = p

    def sort_key(p: dict):
        if fq == "gene_rank":
            ranks = [p[g]["rank"] for g in ("good", "poor")
                     if isinstance(p.get(g), dict)]
            return (0 if ranks else 1,
                    min(ranks) if ranks else 1 << 30,
                    str(p.get("bundle")))
        ov = p.get("overlap")
        return (0 if ov is not None else 1, -(ov or 0.0),
                str(p.get("bundle")))

    return sorted(seen.values(), key=sort_key)


def read_vectors_txt(path: str) -> Tuple[List[str], np.ndarray]:
    """Parse a ``<NAME>_vectors.txt`` output back into (genes,
    float32 [G, H]) — the lazy-republish source when a bundle is lost
    or tampered but the durable record's text outputs survive."""
    genes: List[str] = []
    rows: List[List[float]] = []
    with open(path) as f:
        header = f.readline()
        if not header.startswith("GeneSymbol"):
            raise ValueError(f"{path}: not a vectors file")
        for ln in f:
            parts = ln.rstrip("\n").split("\t")
            if len(parts) < 2:
                continue
            genes.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
    return genes, np.asarray(rows, dtype=np.float32)
