"""Wire protocol for ``g2vec serve``: JSONL over a local UNIX socket.

One request object per connection, newline-terminated; the daemon answers
with a stream of newline-delimited JSON events and closes the stream after
the terminal event (``accepted``/``rejected`` + per-job progress ending in
``job_done``/``job_failed``/``job_cancelled``/``job_deadline_exceeded``/
``job_drained`` for submits; a single event for
``status``/``ping``/``shutdown``/``cancel``/``drain``). Line-delimited
JSON keeps both sides trivially incremental — the daemon can stream a
job's events as they happen and a shell client is one ``nc -U`` away.

The same socket also answers plain HTTP ``GET /status`` (detected from the
request's first bytes), so ``curl --unix-socket <sock> http://g2vec/status``
works without a client library.

Requests::

    {"op": "submit", "tenant": "alice", "job": {...},    # see daemon.py
     "priority": "interactive", "deadline_s": 120}       # both optional
    {"op": "status"} | {"op": "ping"} | {"op": "shutdown"}
    {"op": "cancel", "job_id": "j0001-..."}              # cooperative
    {"op": "drain"}     # stop admitting, checkpoint, journal, exit 0
"""
from __future__ import annotations

import json
from typing import IO, Optional

#: One line must fit a submit with a large manifest, with headroom; a
#: longer line is a protocol error, not an OOM.
MAX_LINE_BYTES = 8 << 20


class ProtocolError(ValueError):
    """A malformed request/response line."""


def write_event(f: IO[bytes], obj: dict) -> None:
    """One JSONL record, flushed — event streams must not sit in buffers."""
    f.write(json.dumps(obj).encode() + b"\n")
    f.flush()


def read_event(f: IO[bytes]) -> Optional[dict]:
    """The next JSONL record, or None on a closed stream."""
    line = f.readline(MAX_LINE_BYTES)
    if not line:
        return None
    if len(line) >= MAX_LINE_BYTES and not line.endswith(b"\n"):
        raise ProtocolError(
            f"line exceeds {MAX_LINE_BYTES} bytes — truncated or not a "
            f"g2vec serve peer")
    try:
        obj = json.loads(line)
    except ValueError as e:
        raise ProtocolError(f"not a JSON line: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"expected a JSON object per line, got {type(obj).__name__}")
    return obj
