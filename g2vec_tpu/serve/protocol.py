"""Wire protocol for ``g2vec serve``: JSONL over a UNIX or TCP socket.

One request object per connection, newline-terminated; the daemon answers
with a stream of newline-delimited JSON events and closes the stream after
the terminal event (``accepted``/``rejected`` + per-job progress ending in
``job_done``/``job_failed``/``job_cancelled``/``job_deadline_exceeded``/
``job_drained`` for submits; a single event for
``status``/``ping``/``shutdown``/``cancel``/``drain``). Line-delimited
JSON keeps both sides trivially incremental — the daemon can stream a
job's events as they happen and a shell client is one ``nc -U`` away.

The same socket also answers plain HTTP ``GET /status`` (detected from the
request's first bytes), so ``curl --unix-socket <sock> http://g2vec/status``
works without a client library.

Requests::

    {"op": "submit", "tenant": "alice", "job": {...},    # see daemon.py
     "priority": "interactive", "deadline_s": 120}       # both optional
    {"op": "status"} | {"op": "ping"} | {"op": "shutdown"}
    {"op": "cancel", "job_id": "j0001-..."}              # cooperative
    {"op": "result", "job_id": "i...",   # durable record or "pending";
     "fields": ["event", "variants"],    # optional top-level selector
     "max_bytes": 65536}                 # optional response size cap
    {"op": "query", "q": "neighbors", "job_id": "i...",  # read plane:
     "variant": "base", "gene": "TP53", "k": 10}         # see QUERY_KEYS
    {"op": "update", "job_id": "i...", "job": {...},     # incremental
     "epochs": 10}      # delta re-walk + warm-start, see UPDATE_KEYS
    {"op": "drain"}     # stop admitting, checkpoint, journal, exit 0

Addressing: an address containing ``host:port`` dials TCP, anything else
is a UNIX socket path — :func:`parse_addr` / :func:`dial` keep client,
router, and tooling on one resolver. TCP adds two request fields:
``auth_token`` (checked at admission for mutating ops when the listener
was started with a token) and ``idem_key`` (client-generated idempotency
key; resubmits with the same key are acked once, see daemon.py).
"""
from __future__ import annotations

import hashlib
import json
import re
import socket
from typing import IO, Optional, Tuple, Union

#: Client-generated idempotency keys (``idem_key`` in a submit payload).
#: Lives here — not in daemon.py — because the jax-free router must
#: derive job ids too (sticky routing: a key the fleet has seen resolves
#: to its existing home replica, never to a fresh ring placement).
MAX_IDEM_KEY = 128


def idem_job_id(idem_key: str) -> str:
    """Derive the job_id from the idempotency key. Same key -> same id
    -> same journal/checkpoint/result names on ANY replica: the naming
    scheme IS the exactly-once mechanism."""
    return "i" + hashlib.sha256(idem_key.encode()).hexdigest()[:12]

#: One line must fit a submit with a large manifest, with headroom; a
#: longer line is a protocol error, not an OOM.
MAX_LINE_BYTES = 8 << 20

#: The submit-payload envelope vocabulary. Every key daemon.py or
#: router.py reads off a submit payload must be declared here — the
#: config/doc-drift checker (analyze/configdoc.py) enforces it, so a
#: typo'd ``payload.get("pirority")`` fails tier-1 instead of silently
#: returning the default. Job-CONTENT keys (the ``job`` object's
#: fields) are governed separately by config.SERVE_JOB_KEYS.
#: ``requeue``/``submitted_at`` are router-internal (set only by the
#: failover journal migration): requeue skips the tenant-quota and
#: shed gates — the job already paid admission once and the client
#: holds an ack — and submitted_at carries the ORIGINAL admission
#: time so a replica death never resets a deadline clock. Because
#: every client shares the fleet ``auth_token``, that token cannot
#: prove router-ness: both fields are honored only when the payload
#: carries the target replica's ``relay_token`` (a per-state-dir
#: secret readable only via the replica's filesystem — the router
#: co-hosts the state dirs, network tenants do not), and the router
#: strips all three from externally received submits before relaying.
#: ``router_epoch`` is the leadership fencing epoch (serve/leader.py):
#: a router holding the lease stamps every mutating command with its
#: epoch, daemons persist the highest epoch they have witnessed, and a
#: mutating command carrying a LOWER epoch gets a structured
#: ``stale_epoch`` reject — a zombie ex-leader that lost the lease
#: mid-partition can no longer fence replicas or migrate journals.
#: Absent/0 means "no leadership machinery" (single-router fleets and
#: degraded-mode clients) and is always accepted.
SUBMIT_KEYS = ("op", "job", "tenant", "priority", "deadline_s",
               "idem_key", "job_id", "auth_token", "requeue",
               "submitted_at", "relay_token", "router_epoch")

#: The query-request envelope vocabulary (the read plane's twin of
#: SUBMIT_KEYS). daemon.py/router.py bind a query payload to the
#: conventional name ``qreq`` and the same checker lints every
#: ``qreq["k"]`` / ``qreq.get("k")`` site against this tuple. ``q``
#: names the sub-op (inventory.QUERY_SUBOPS: neighbors /
#: topk_biomarkers / meta / list); ``variant`` selects a lane of a
#: multi-variant job (optional when the job has exactly one).
#: ``mode`` picks the retrieval path (``approx`` — the IVF index with
#: exact rescoring, the default — or ``exact``, the ground-truth
#: blocked kernel) and ``nprobe`` widens the approx probe; both ride
#: the cache key so approx and exact results never collide.
QUERY_KEYS = ("op", "q", "job_id", "variant", "gene", "k", "mode",
              "nprobe", "auth_token")

#: The federated-query envelope vocabulary: ``fqreq`` reads in
#: daemon.py/router.py are linted against this tuple. ``fq`` names the
#: cross-bundle sub-op (inventory.FQUERY_SUBOPS: ``gene_rank`` — which
#: bundles rank ``gene`` in their top-k prognostic scores — or
#: ``bundle_overlap`` — bundles ranked by neighbor-set overlap with a
#: reference bundle's neighborhood of ``gene``). ``job_id``/``variant``
#: name the reference bundle for ``bundle_overlap``; ``ref_genes`` is
#: the router-resolved reference neighbor list it forwards to replicas
#: so every partial is scored against the same reference.
FQUERY_KEYS = ("op", "fq", "gene", "k", "mode", "nprobe", "job_id",
               "variant", "ref_genes", "auth_token")

#: The update-request envelope vocabulary: ``ureq`` reads in
#: daemon.py/router.py are linted against this tuple. ``update`` is the
#: write half of the read plane: ``job_id``/``variant`` name the target
#: bundle (the prior generation), ``job`` carries the UPDATED input
#: config (same vocabulary as a submit's ``job``, validated by
#: config.SERVE_JOB_KEYS), ``epochs`` bounds the warm-start fine-tune
#: (0 = the engine's default cap). Updates are idempotency-keyed and
#: journaled exactly like submits — ``idem_key`` resubmits ack the same
#: derived id; a SIGKILL mid-update replays from the journal — and the
#: router sticky-routes them to the target bundle's home replica so the
#: generation pointer has exactly one writer. ``requeue``/
#: ``submitted_at``/``relay_token``/``router_epoch`` carry the same
#: failover/fencing semantics as SUBMIT_KEYS.
UPDATE_KEYS = ("op", "job_id", "variant", "job", "tenant", "epochs",
               "priority", "deadline_s", "idem_key", "auth_token",
               "requeue", "submitted_at", "relay_token", "router_epoch")

#: The result-request envelope vocabulary: ``rreq`` reads in
#: daemon.py/router.py are linted against this tuple. ``fields``
#: selects top-level record keys; ``max_bytes`` caps the serialized
#: response (the server-side ``--max-result-bytes`` bound applies
#: regardless — a giant durable record must not blow the line protocol
#: in reverse).
RESULT_KEYS = ("op", "job_id", "fields", "max_bytes", "auth_token")


class ProtocolError(ValueError):
    """A malformed request/response line."""


def bound_record(rec: dict, fields, max_bytes: Optional[int],
                 server_cap: int) -> dict:
    """Apply the ``result`` op's field selector and size bound.

    ``fields`` (optional list) keeps only those top-level record keys
    (plus ``event``/``job_id`` so the response stays self-describing);
    the effective cap is the smaller of the client's ``max_bytes`` and
    the server's ``--max-result-bytes``. An over-cap record becomes a
    structured ``oversized_result`` error naming the available fields
    so the client can re-ask for a subset — it is never truncated
    mid-JSON. Shared by daemon and router so both listeners bound
    identically.
    """
    cap = int(server_cap)
    if max_bytes:
        cap = min(cap, int(max_bytes))
    if fields is not None:
        if not (isinstance(fields, list)
                and all(isinstance(k, str) for k in fields)):
            return {"event": "error", "error": "bad_fields",
                    "detail": "fields must be a list of strings"}
        keep = set(fields) | {"event", "job_id"}
        rec = {k: v for k, v in rec.items() if k in keep}
    size = len(json.dumps(rec).encode())
    if size > cap:
        return {"event": "error", "error": "oversized_result",
                "job_id": rec.get("job_id"), "bytes": size,
                "max_bytes": cap,
                "fields_available": sorted(rec.keys())}
    return rec


#: ``host:port`` — hostname/IPv4 literal, no scheme. A bare path never
#: matches (paths contain ``/`` or no colon), so UNIX sockets stay the
#: default and nothing existing re-resolves.
_TCP_ADDR = re.compile(r"^([A-Za-z0-9._-]+):([0-9]{1,5})$")


def parse_addr(addr: str) -> Union[Tuple[str, int], str]:
    """``"host:port"`` → ``(host, port)`` for TCP; anything else is
    returned unchanged as a UNIX socket path."""
    m = _TCP_ADDR.match(addr)
    if m:
        port = int(m.group(2))
        if port > 65535:
            raise ProtocolError(f"port out of range in {addr!r}")
        return m.group(1), port
    return addr


def dial(addr: str, timeout: Optional[float] = None) -> socket.socket:
    """Connect to a serve endpoint — TCP for ``host:port``, UNIX
    otherwise. The returned socket has ``timeout`` applied (None = block
    forever), matching both listeners' JSONL framing."""
    parsed = parse_addr(addr)
    if isinstance(parsed, tuple):
        sock = socket.create_connection(parsed, timeout=timeout)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(parsed)
    sock.settimeout(timeout)
    return sock


def write_event(f: IO[bytes], obj: dict) -> None:
    """One JSONL record, flushed — event streams must not sit in buffers."""
    f.write(json.dumps(obj).encode() + b"\n")
    f.flush()


def read_event(f: IO[bytes]) -> Optional[dict]:
    """The next JSONL record, or None on a closed stream."""
    line = f.readline(MAX_LINE_BYTES)
    if not line:
        return None
    if len(line) >= MAX_LINE_BYTES and not line.endswith(b"\n"):
        raise ProtocolError(
            f"line exceeds {MAX_LINE_BYTES} bytes — truncated or not a "
            f"g2vec serve peer")
    try:
        obj = json.loads(line)
    except ValueError as e:
        raise ProtocolError(f"not a JSON line: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"expected a JSON object per line, got {type(obj).__name__}")
    return obj
