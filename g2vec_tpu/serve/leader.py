"""Shared-disk router leadership lease with fencing epochs.

jax-free: the router stack must boot on accelerator-free hosts, and a
standby router spends most of its life doing nothing but watching one
file.  This module owns three tiny disk protocols, all built on the
repo's atomic tmp+``os.replace`` publication idiom:

1. **The leadership lease** (``<fleet_dir>/leader.json``).  One router
   is leader at a time; the file records ``(epoch, holder, renewed_at,
   ttl_s)``.  A lease is *expired* when ``now`` exceeds **either** the
   recorded ``renewed_at + ttl`` or the file's mtime plus ttl — the
   mtime backstop means a writer with a skewed (future) clock cannot
   publish an unexpirable lease.  A healthy leader renews at ttl/3, so
   both clocks stay fresh and the aggressive disjunction never fires
   spuriously; and even a wrongly stolen lease is SAFE (the old
   holder's next renew sees the takeover, drops to zombie, and every
   mutation it still emits is fenced by epoch) — early takeover costs
   availability at worst, never exactly-once.  Acquisition is
   claim-then-confirm: write an ``epoch+1`` claim, wait ``settle_s``,
   re-read, and hold only if the survivor of the rename race is our
   claim.  Two standbys racing both rename; exactly one file survives;
   the loser's confirm read sees the winner and reports failure.
2. **The epoch hint** (``<fleet_dir>/leader.epoch``).  Written before
   every lease write, it keeps the fencing epoch monotone even when the
   lease file itself is torn (a half-written lease must never reset
   epochs — a zombie holding the old epoch would suddenly look fresh).
3. **Fence markers** (``<state_dir>/fenced``).  Before migrating the
   journal of a replica it could not *locally verify* dead, the leader
   bumps its epoch and drops a marker in the replica's state dir.  The
   (possibly partitioned, possibly perfectly healthy) daemon checks the
   marker at shard/superstep boundaries and self-quarantines: parks
   in-flight work, closes admission, and stops publishing results and
   inventory.  A torn marker reads as *fenced* — the conservative
   direction, since the marker only ever exists because a migration is
   underway.

Epoch 0 everywhere means "no leadership machinery": single-router
fleets never write a lease, never attach epochs, and behave exactly as
they did before this module existed.
"""
from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
import uuid
from typing import Callable, Optional, Tuple

LEASE_FILE = "leader.json"
EPOCH_HINT_FILE = "leader.epoch"
FENCE_MARKER = "fenced"
ROUTER_EPOCH_FILE = "router_epoch"

#: Default lease TTL when HA mode is enabled without an explicit value.
DEFAULT_TTL_S = 5.0


@dataclasses.dataclass(frozen=True)
class LeaseState:
    """A parsed lease file; ``expired`` is computed by the reader."""
    epoch: int
    holder: str
    renewed_at: float
    ttl_s: float


def _write_atomic(path: str, data: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def read_lease(path: str) -> Optional[LeaseState]:
    """Parse the lease file; ``None`` for absent *or torn* files.

    Torn lease files do not block takeover (expiry falls back to the
    epoch hint for monotonicity), and they do not grant leadership.
    """
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw = json.load(fh)
        return LeaseState(epoch=int(raw["epoch"]),
                          holder=str(raw["holder"]),
                          renewed_at=float(raw["renewed_at"]),
                          ttl_s=float(raw["ttl_s"]))
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _lease_expired(path: str, st: Optional[LeaseState],
                   now: float) -> bool:
    if st is None:
        return True
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return True
    # Expired when EITHER clock says so: the filesystem mtime backstops
    # a writer whose own clock is skewed into the future (its
    # renewed_at would otherwise never age out), and vice versa. A
    # renewing leader keeps both fresh; a wrong steal is epoch-fenced.
    return now > st.renewed_at + st.ttl_s or now > mtime + st.ttl_s


class LeaderLease:
    """One router's handle on the shared-disk lease.

    ``held`` and ``epoch`` are deliberately separate: when the lease is
    lost, ``held`` drops to False but ``epoch`` KEEPS its last value —
    a zombie ex-leader must go on stamping its (now stale) epoch on
    every mutating command so the daemons' ``stale_epoch`` check can
    reject it.  Zeroing the epoch on loss would make the zombie's
    commands arrive epoch-less, which daemons accept for PR 16
    compatibility — exactly the hole fencing exists to close.  All
    mutation happens under ``_lock``; callers read ``.epoch`` freely
    (int reads are atomic).
    """

    def __init__(self, fleet_dir: str, ttl_s: float = DEFAULT_TTL_S,
                 holder: Optional[str] = None,
                 settle_s: float = 0.05) -> None:
        self.fleet_dir = fleet_dir
        self.path = os.path.join(fleet_dir, LEASE_FILE)
        self.hint_path = os.path.join(fleet_dir, EPOCH_HINT_FILE)
        self.ttl_s = float(ttl_s)
        self.settle_s = float(settle_s)
        self.holder = holder or (
            f"{socket.gethostname()}:{os.getpid()}:"
            f"{uuid.uuid4().hex[:8]}")
        self._lock = threading.Lock()
        #: guarded-by: _lock
        self.epoch = 0
        #: guarded-by: _lock
        self._held = False

    # ---- epoch hint -----------------------------------------------------

    def _read_hint(self) -> int:
        try:
            with open(self.hint_path, "r", encoding="utf-8") as fh:
                return int(fh.read().strip())
        except (OSError, ValueError):
            return 0

    def _write_hint(self, epoch: int) -> None:
        _write_atomic(self.hint_path,
                      str(max(epoch, self._read_hint())))

    # ---- lease I/O ------------------------------------------------------

    def _write_lease(self, epoch: int, now: float) -> None:
        self._write_hint(epoch)
        _write_atomic(self.path, json.dumps({
            "epoch": epoch, "holder": self.holder,
            "renewed_at": now, "ttl_s": self.ttl_s}))

    def peek(self) -> Tuple[Optional[LeaseState], bool]:
        """(lease state, expired). Torn files read as (None, True)."""
        st = read_lease(self.path)
        return st, _lease_expired(self.path, st, time.time())

    @property
    def held(self) -> bool:
        return self._held

    # ---- protocol -------------------------------------------------------

    def acquire(self) -> bool:
        """Claim leadership if the lease is absent, ours, or expired.

        Claim-then-confirm: the rename race between two concurrent
        claimants has exactly one survivor, and only the claimant whose
        (holder, epoch) survives the settle window holds the lease.
        """
        with self._lock:
            now = time.time()
            st = read_lease(self.path)
            expired = _lease_expired(self.path, st, now)
            if st is not None and not expired and \
                    st.holder != self.holder:
                self._held = False
                return False
            if st is not None and not expired and \
                    st.holder == self.holder:
                self.epoch = st.epoch
                self._held = True
                return True
            prev = max(st.epoch if st else 0, self._read_hint())
            claim = prev + 1
            self._write_lease(claim, now)
            time.sleep(self.settle_s)
            cur = read_lease(self.path)
            if cur is not None and cur.holder == self.holder and \
                    cur.epoch == claim:
                self.epoch = claim
                self._held = True
                return True
            self._held = False
            return False

    def renew(self) -> bool:
        """Refresh the ttl; returns False (dropping ``held``, KEEPING
        the stale epoch) if the lease was taken over — the caller is
        now a zombie whose stamped commands must fail the daemons'
        stale-epoch check."""
        with self._lock:
            if not self._held:
                return False
            cur = read_lease(self.path)
            if cur is None or cur.holder != self.holder or \
                    cur.epoch != self.epoch:
                self._held = False
                return False
            self._write_lease(self.epoch, time.time())
            return True

    def bump(self) -> int:
        """Advance the fencing epoch while holding the lease (used
        before a false-dead journal migration).  Returns the new epoch,
        or 0 if the lease is not held / was lost (the stale epoch is
        kept for stamping, per the class contract)."""
        with self._lock:
            if not self._held:
                return 0
            cur = read_lease(self.path)
            if cur is None or cur.holder != self.holder:
                self._held = False
                return 0
            self.epoch = cur.epoch + 1
            self._write_lease(self.epoch, time.time())
            return self.epoch

    def release(self) -> None:
        """Drop the lease file (best-effort) so a standby can take over
        without waiting out the ttl.  The epoch hint stays behind —
        epochs never go backwards."""
        with self._lock:
            if not self._held:
                return
            cur = read_lease(self.path)
            if cur is not None and cur.holder == self.holder:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            self._held = False


def wait_for_leadership(lease: LeaderLease, poll_s: float = 0.25,
                        stop: Optional[threading.Event] = None,
                        on_wait: Optional[Callable[[], None]] = None,
                        ) -> bool:
    """Standby loop: watch the lease until it expires, then take over.

    Returns True once ``lease.acquire()`` confirms, False if ``stop``
    was set first.  ``on_wait`` (if given) is invoked once per poll —
    the router uses it to keep its adopted view of the fleet warm.
    """
    while stop is None or not stop.is_set():
        st, expired = lease.peek()
        if expired or (st is not None and st.holder == lease.holder):
            if lease.acquire():
                return True
        if on_wait is not None:
            on_wait()
        if stop is not None:
            if stop.wait(poll_s):
                return False
        else:
            time.sleep(poll_s)
    return False


# ---- fence markers ------------------------------------------------------


def fence_marker_path(state_dir: str) -> str:
    return os.path.join(state_dir, FENCE_MARKER)


def write_fence_marker(state_dir: str, epoch: int) -> None:
    """Drop the per-replica quarantine marker.  Written by the leader
    *before* it migrates an unreachable replica's journal, so by the
    time duplicated work could exist the original has a kill order on
    disk."""
    _write_atomic(fence_marker_path(state_dir),
                  json.dumps({"epoch": int(epoch),
                              "fenced_at": time.time()}))


def read_fence_marker(state_dir: str) -> Optional[int]:
    """Fencing epoch from the marker; None when absent.  A torn marker
    reads as epoch 0 — still fenced: the marker only exists because a
    migration started, so the conservative parse is the safe one."""
    path = fence_marker_path(state_dir)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw = fh.read()
    except OSError:
        return None
    try:
        return int(json.loads(raw)["epoch"])
    except (ValueError, KeyError, TypeError):
        return 0


def clear_fence_marker(state_dir: str) -> None:
    try:
        os.unlink(fence_marker_path(state_dir))
    except OSError:
        pass


# ---- persisted router-epoch (daemon side) -------------------------------


def read_epoch_file(path: str) -> int:
    """Highest router epoch a daemon has ever witnessed (0 on absent or
    torn — a torn epoch file must not manufacture a high epoch that
    would reject the *real* leader)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        return 0


def write_epoch_file(path: str, epoch: int) -> None:
    _write_atomic(path, str(int(epoch)))
