"""Client for ``g2vec serve`` daemons and routers (CLI, bench, tests).

Talks the protocol.py JSONL dialect over a UNIX socket path or a TCP
``host:port`` address — :func:`protocol.dial` picks the transport, so
every helper here works unchanged against a single daemon or the
replicated-fleet router. The one failure mode worth a dedicated type:
the server dying mid-job (SIGKILL, preemption) closes the stream without
a terminal event — :class:`ServeConnectionLost` carries the job_id so
the caller can fall back to :func:`poll_result` (filesystem) or
:func:`poll_result_net` (the ``result`` op, re-resolved through the
router on every attempt), which read the durable record that survives
any replica's death.

HA fleets add two layers on top:

- :func:`submit_and_wait` and :func:`poll_result_net` accept a LIST of
  router addresses (active + standbys); attempts rotate through the
  list under the existing jittered backoff, so a client survives a
  router takeover without reconfiguration.
- When NO router answers at all (both routers partitioned away), the
  ``degraded_*`` helpers fall back to the fleet's published per-replica
  ``tcp_addr`` files: read-only ops (status / result / query) fan out
  to the replicas directly, and keyed submits go to a deterministically
  chosen replica — the idempotency key derives the job_id, so once a
  router heals its sticky scan reconciles the degraded-mode submit with
  the journal/result exactly once.
"""
from __future__ import annotations

import glob
import json
import os
import random
import socket
import time
import uuid
import zlib
from typing import Iterator, List, Optional, Sequence, Union

from g2vec_tpu.serve import protocol

#: A serve endpoint: one address, or a rotation list (router + standbys).
Addr = Union[str, Sequence[str]]


def _rotation(socket_path: Addr) -> List[str]:
    """Normalize an address-or-list into a non-empty rotation list."""
    if isinstance(socket_path, (list, tuple)):
        addrs = [a for a in socket_path if a]
        if not addrs:
            raise ValueError("empty address list")
        return list(addrs)
    return [socket_path]


class ServeConnectionLost(RuntimeError):
    """The daemon's stream closed before the job's terminal event."""

    def __init__(self, msg: str, job_id: Optional[str] = None):
        super().__init__(msg)
        self.job_id = job_id


class ServeTimeout(TimeoutError):
    """A client-side wait expired. Always names the job it was waiting
    for — a bare ``socket.timeout`` tells an operator nothing."""

    def __init__(self, msg: str, job_id: Optional[str] = None):
        super().__init__(msg)
        self.job_id = job_id


class ServeShed(RuntimeError):
    """The fleet shed this job at admission (deadline-aware load
    shedding or a tenant rate limit) on every bounded retry.
    Structured: names the ``tenant`` and ``job_id`` the operator needs,
    plus the server's last ``retry_after_s`` advice — the caller can
    honor it on a slower retry loop of its own."""

    def __init__(self, msg: str, tenant: Optional[str] = None,
                 job_id: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.tenant = tenant
        self.job_id = job_id
        self.retry_after_s = retry_after_s


#: Admission rejections that carry ``retry_after_s`` — transient by
#: contract (the server is saying "later", not "never").
_SHED_ERRORS = ("shed", "tenant_quota")


def request(socket_path: str, payload: dict,
            timeout: Optional[float] = None) -> Iterator[dict]:
    """Send one request; yield the server's JSONL events until it closes
    the stream. ``timeout`` bounds each socket read, not the whole job.
    ``socket_path`` may be a UNIX path or ``host:port``."""
    s = protocol.dial(socket_path, timeout=timeout)
    try:
        f = s.makefile("rwb")
        protocol.write_event(f, payload)
        while True:
            ev = protocol.read_event(f)
            if ev is None:
                return
            yield ev
    finally:
        s.close()


#: Terminal stream events (``job_drained`` is terminal for THIS stream —
#: the job itself pauses, stays journaled, and resumes after restart).
_TERMINAL = ("job_done", "job_failed", "job_cancelled",
             "job_deadline_exceeded", "job_drained")


def submit_job(socket_path: str, job: dict, tenant: str = "default",
               timeout: Optional[float] = None,
               priority: Optional[str] = None,
               deadline_s: Optional[float] = None,
               idem_key: Optional[str] = None,
               auth_token: Optional[str] = None) -> List[dict]:
    """Submit ``job`` and stream its events to completion. Returns every
    event received ([..., terminal event] on success/failure, or
    [rejected] on admission refusal). Raises :class:`ServeConnectionLost`
    if the stream dies first (daemon killed mid-job — poll_result picks
    the job back up after the supervisor relaunch) and
    :class:`ServeTimeout` when a socket read outlives ``timeout``."""
    events: List[dict] = []
    job_id = None
    payload = {"op": "submit", "tenant": tenant, "job": job}
    if priority is not None:
        payload["priority"] = priority
    if deadline_s is not None:
        payload["deadline_s"] = deadline_s
    if idem_key is not None:
        payload["idem_key"] = idem_key
    if auth_token is not None:
        payload["auth_token"] = auth_token
    try:
        for ev in request(socket_path, payload, timeout=timeout):
            events.append(ev)
            kind = ev.get("event")
            if kind == "accepted":
                job_id = ev.get("job_id")
            if kind == "rejected" or kind in _TERMINAL:
                return events
    except socket.timeout:
        raise ServeTimeout(
            f"no event from the daemon within {timeout}s while waiting "
            f"on job {job_id or '<unacknowledged>'}",
            job_id=job_id) from None
    raise ServeConnectionLost(
        f"daemon stream closed before job "
        f"{job_id or '<unacknowledged>'} finished", job_id=job_id)


def update_job(socket_path: str, target_job_id: str, job: dict,
               idem_key: str, variant: Optional[str] = None,
               epochs: int = 0, tenant: str = "default",
               timeout: Optional[float] = None,
               priority: Optional[str] = None,
               deadline_s: Optional[float] = None,
               auth_token: Optional[str] = None) -> List[dict]:
    """Incrementally retrain ``target_job_id``'s published bundle from
    the updated inputs in ``job`` and stream events to completion —
    the ``update`` op. ``idem_key`` is REQUIRED (the op is
    idempotency-keyed: a resubmit after a lost ack dedups instead of
    retraining twice). ``epochs`` bounds the warm-start fine-tune
    (0 lets the daemon pick). Same return/raise contract as
    :func:`submit_job`."""
    events: List[dict] = []
    payload = {"op": "update", "job_id": target_job_id, "job": job,
               "idem_key": idem_key, "tenant": tenant}
    if variant is not None:
        payload["variant"] = variant
    if epochs:
        payload["epochs"] = int(epochs)
    if priority is not None:
        payload["priority"] = priority
    if deadline_s is not None:
        payload["deadline_s"] = deadline_s
    if auth_token is not None:
        payload["auth_token"] = auth_token
    try:
        for ev in request(socket_path, payload, timeout=timeout):
            events.append(ev)
            kind = ev.get("event")
            if kind == "rejected" or kind in _TERMINAL:
                return events
    except socket.timeout:
        raise ServeTimeout(
            f"no event from the daemon within {timeout}s while waiting "
            f"on the update of {target_job_id}") from None
    raise ServeConnectionLost(
        f"daemon stream closed before the update of {target_job_id} "
        f"finished")


def _one(socket_path: str, op: str, timeout: Optional[float],
         auth_token: Optional[str] = None, **fields) -> dict:
    payload = {"op": op, **fields}
    if auth_token is not None:
        payload["auth_token"] = auth_token
    for ev in request(socket_path, payload, timeout=timeout):
        return ev
    raise ServeConnectionLost(f"no response to {op!r}")


def status(socket_path: str, timeout: Optional[float] = 10.0) -> dict:
    return _one(socket_path, "status", timeout)


def ping(socket_path: str, timeout: Optional[float] = 5.0) -> dict:
    return _one(socket_path, "ping", timeout)


def shutdown(socket_path: str, timeout: Optional[float] = 10.0,
             auth_token: Optional[str] = None) -> dict:
    return _one(socket_path, "shutdown", timeout, auth_token=auth_token)


def cancel(socket_path: str, job_id: str,
           timeout: Optional[float] = 10.0,
           auth_token: Optional[str] = None) -> dict:
    """Cancel a queued (immediate) or running (cooperative, next
    shard/chunk boundary) job."""
    return _one(socket_path, "cancel", timeout, auth_token=auth_token,
                job_id=job_id)


def drain(socket_path: str, timeout: Optional[float] = 10.0,
          auth_token: Optional[str] = None) -> dict:
    """Ask the daemon to drain gracefully: admission closes, in-flight
    streaming jobs checkpoint, everything unfinished stays journaled."""
    return _one(socket_path, "drain", timeout, auth_token=auth_token)


def query(socket_path: str, q: str, job_id: Optional[str] = None,
          variant: Optional[str] = None, gene: Optional[str] = None,
          k: Optional[int] = None, timeout: Optional[float] = 30.0,
          auth_token: Optional[str] = None, mode: Optional[str] = None,
          nprobe: Optional[int] = None) -> dict:
    """One read-plane query (``neighbors`` / ``topk_biomarkers`` /
    ``meta`` / ``list``) against a daemon or the router — the router
    routes it to the bundle's home replica and answers from disk itself
    when that replica is dead. Token-gated like the mutators: query
    responses carry tenant embeddings/scores. ``mode`` picks the
    retrieval path (``approx`` default / ``exact`` ground truth);
    ``nprobe`` widens the approx probe."""
    fields = {"q": q}
    if job_id is not None:
        fields["job_id"] = job_id
    if variant is not None:
        fields["variant"] = variant
    if gene is not None:
        fields["gene"] = gene
    if k is not None:
        fields["k"] = k
    if mode is not None:
        fields["mode"] = mode
    if nprobe is not None:
        fields["nprobe"] = nprobe
    return _one(socket_path, "query", timeout, auth_token=auth_token,
                **fields)


def fquery(socket_path: str, fq: str, gene: str,
           k: Optional[int] = None, mode: Optional[str] = None,
           nprobe: Optional[int] = None, job_id: Optional[str] = None,
           variant: Optional[str] = None,
           ref_genes: Optional[List[str]] = None,
           timeout: Optional[float] = 30.0,
           auth_token: Optional[str] = None) -> dict:
    """One federated cross-bundle query (``gene_rank`` /
    ``bundle_overlap``). Against the router it scatter-gathers over the
    replica fleet (answering dead replicas' bundles from shared disk,
    with per-bundle ``served_by``/``replica_down`` attribution);
    against a single daemon it covers that daemon's bundles.
    ``bundle_overlap`` needs either ``ref_genes`` or a reference
    ``job_id``/``variant`` the server resolves into one."""
    fields: dict = {"fq": fq, "gene": gene}
    if k is not None:
        fields["k"] = k
    if mode is not None:
        fields["mode"] = mode
    if nprobe is not None:
        fields["nprobe"] = nprobe
    if job_id is not None:
        fields["job_id"] = job_id
    if variant is not None:
        fields["variant"] = variant
    if ref_genes is not None:
        fields["ref_genes"] = ref_genes
    return _one(socket_path, "fquery", timeout, auth_token=auth_token,
                **fields)


def result(socket_path: str, job_id: str,
           fields: Optional[List[str]] = None,
           max_bytes: Optional[int] = None,
           timeout: Optional[float] = 30.0,
           auth_token: Optional[str] = None) -> dict:
    """One ``result`` lookup with the PR 15 response bounds: ``fields``
    selects top-level record keys, ``max_bytes`` caps the serialized
    response (an over-cap record comes back as a structured
    ``oversized_result`` error naming the available fields)."""
    extra = {}
    if fields is not None:
        extra["fields"] = fields
    if max_bytes is not None:
        extra["max_bytes"] = max_bytes
    return _one(socket_path, "result", timeout, auth_token=auth_token,
                job_id=job_id, **extra)


def submit_and_wait(socket_path: Addr, job: dict, tenant: str = "default",
                    state_dir: Optional[str] = None,
                    timeout: Optional[float] = None,
                    poll_deadline_s: float = 300.0,
                    priority: Optional[str] = None,
                    deadline_s: Optional[float] = None,
                    retries: int = 3, backoff: float = 0.25,
                    jitter: float = 0.25,
                    rng: Optional[random.Random] = None,
                    idem_key: Optional[str] = None,
                    auth_token: Optional[str] = None,
                    shed_retries: int = 3) -> dict:
    """Submit a job and return its terminal record, surviving daemon
    restarts AND replica failover behind a router.

    Every attempt carries the same idempotency key (auto-minted when the
    caller passes none), so a resubmission after a lost ack can never run
    the job twice — the server dedups on the key and answers with the
    original job_id. Transport-level failures retry with exponential
    backoff plus jitter (``backoff * 2**attempt + U[0, jitter)`` seconds —
    the jitter keeps a fleet of clients from re-dialing a relaunching
    daemon in lockstep). Recovery paths:

    - connect refused / reset BEFORE acceptance → resubmit with the same
      idem key (either nothing was journaled, or the dedup table
      re-acks the original);
    - stream lost AFTER acceptance (:class:`ServeConnectionLost` with a
      job_id) → the job is journaled somewhere; poll the durable record
      via :func:`poll_result` when a ``state_dir`` is known, else via
      :func:`poll_result_net` — which re-dials ``socket_path`` (the
      router, typically) on every attempt, so the answer arrives even
      after the job migrated replicas. Never resubmit here — the poll
      is strictly read-only.

    A ``rejected`` answer whose error is ``shed`` or ``tenant_quota``
    is the fleet's structured "try later": back off for the advised
    ``retry_after_s`` (plus jitter — an entire shed flash-crowd must not
    return in lockstep) and resubmit with the SAME idem key, up to
    ``shed_retries`` extra attempts; past that, raise
    :class:`ServeShed` naming the tenant and job_id. Shed retries spend
    their own budget, not the transport-retry one — a load-shedding
    fleet is healthy, a connection-refusing one is not.

    ``socket_path`` may be a LIST of router addresses (active router
    first, standbys after): each transport retry rotates to the next
    address under the same jittered backoff, so a standby takeover is
    one rotation away instead of a reconfiguration. The idem key makes
    the rotation safe — whichever router finally accepts dedups against
    everything its predecessors journaled.

    Raises :class:`ServeTimeout` naming the job when all retries or the
    result poll expire."""
    rng = rng if rng is not None else random.Random()
    addrs = _rotation(socket_path)
    if idem_key is None:
        idem_key = f"c-{uuid.uuid4().hex}"
    last: Optional[BaseException] = None
    sheds = 0
    attempt = 0
    while attempt <= retries:
        addr = addrs[attempt % len(addrs)]
        try:
            events = submit_job(addr, job, tenant=tenant,
                                timeout=timeout, priority=priority,
                                deadline_s=deadline_s, idem_key=idem_key,
                                auth_token=auth_token)
            ev = events[-1]
            if (ev.get("event") == "rejected"
                    and ev.get("error") in _SHED_ERRORS
                    and ev.get("retry_after_s") is not None):
                if sheds >= shed_retries:
                    raise ServeShed(
                        f"job {ev.get('job_id')} (tenant "
                        f"{ev.get('tenant', tenant)}) shed by admission "
                        f"({ev.get('error')}) on {sheds + 1} attempt(s); "
                        f"last advice: retry_after_s="
                        f"{ev.get('retry_after_s')}",
                        tenant=ev.get("tenant", tenant),
                        job_id=ev.get("job_id"),
                        retry_after_s=float(ev["retry_after_s"]))
                sheds += 1
                time.sleep(float(ev["retry_after_s"])
                           + rng.uniform(0.0, jitter))
                continue        # same idem key, no transport budget spent
            return ev
        except ServeConnectionLost as e:
            if e.job_id is not None:
                if state_dir is not None:
                    return poll_result(state_dir, e.job_id,
                                       deadline_s=poll_deadline_s)
                return poll_result_net(addrs, e.job_id,
                                       deadline_s=poll_deadline_s,
                                       rng=rng)
            last = e          # unacknowledged — the idem key makes the
            #                   resubmit below safe even if the ack was
            #                   written but never reached us
        except ServeTimeout:
            raise
        except (ConnectionError, FileNotFoundError, OSError) as e:
            last = e
        if attempt < retries:
            time.sleep(backoff * (2 ** attempt) + rng.uniform(0.0, jitter))
        attempt += 1
    raise ServeTimeout(
        f"submit failed after {retries + 1} attempt(s): "
        f"{type(last).__name__}: {last}") from last


def wait_ready(socket_path: str, deadline_s: float = 60.0,
               interval: float = 0.2) -> bool:
    """Poll until the daemon answers ``ping`` (socket may not exist yet
    during startup). True when ready, False at the deadline."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            if ping(socket_path).get("event") == "pong":
                return True
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            pass
        time.sleep(interval)
    return False


def poll_result(state_dir: str, job_id: str, deadline_s: float = 300.0,
                interval: float = 0.25) -> dict:
    """Wait for ``<state_dir>/results/<job_id>.json`` — the durable
    terminal record, written even when no client is connected (and the
    recovery path after :class:`ServeConnectionLost`)."""
    path = os.path.join(state_dir, "results", f"{job_id}.json")
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass        # mid-write; atomic rename makes this brief
        time.sleep(interval)
    raise ServeTimeout(f"no result record for job {job_id} within "
                       f"{deadline_s:.0f}s ({path})", job_id=job_id)


def poll_result_net(socket_path: Addr, job_id: str,
                    deadline_s: float = 300.0, interval: float = 0.5,
                    jitter: float = 0.5,
                    rng: Optional[random.Random] = None) -> dict:
    """Wait for a job's durable terminal record via the ``result`` op —
    the network twin of :func:`poll_result` for clients that cannot see
    the server's filesystem (TCP mode, or any fleet behind the router).

    Re-dials ``socket_path`` on EVERY attempt: when that address is the
    router's, each poll re-resolves to whichever replica currently holds
    the record, so the answer arrives even while the job is migrating
    between replicas mid-failover. Strictly read-only — it can never
    duplicate work, only observe it. Transport errors (the router itself
    restarting, a replica relaunching) back off with jitter so a fleet
    of waiting clients doesn't re-dial in lockstep; ``pending`` answers
    poll at the flat ``interval``. A LIST of addresses (router +
    standbys) rotates to the next entry on each transport failure —
    strictly read-only, so asking every router is always safe.

    Raises :class:`ServeTimeout` naming ``job_id`` at the deadline."""
    rng = rng if rng is not None else random.Random()
    addrs = _rotation(socket_path)
    deadline = time.time() + deadline_s
    fails = 0
    idx = 0
    while time.time() < deadline:
        try:
            for ev in request(addrs[idx % len(addrs)],
                              {"op": "result", "job_id": job_id},
                              timeout=min(30.0, deadline_s)):
                if ev.get("event") not in ("pending", "error"):
                    return ev
                break
            fails = 0
            time.sleep(interval)
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            fails += 1
            idx += 1            # rotate: maybe a standby answers
            time.sleep(min(8.0, interval * (2 ** min(fails, 4)))
                       + rng.uniform(0.0, jitter))
    raise ServeTimeout(f"no result record for job {job_id} within "
                       f"{deadline_s:.0f}s (via {addrs})",
                       job_id=job_id)


# ---- degraded mode (no router answers) ----------------------------------
#
# The fleet's replicas publish their own ``tcp_addr`` files on the shared
# fleet disk; a client that can read that disk can keep working when
# every router is partitioned away or dead. Reads (status / result /
# query) fan out to the replicas directly — they can never duplicate
# work. Submits are allowed ONLY with an idempotency key: the key
# derives the job_id, the chosen replica's dedup table absorbs retries,
# and the first healed router's sticky scan finds the journal entry or
# result record wherever it landed — reconciliation IS the idem key.


def fleet_addrs(fleet_dir: str) -> List[str]:
    """Replica addresses published under ``<fleet_dir>/<name>/state/
    tcp_addr``, sorted by replica name. Replicas that never bound (no
    file) or are mid-relaunch (empty file) are skipped."""
    out: List[str] = []
    for path in sorted(glob.glob(os.path.join(
            fleet_dir, "*", "state", "tcp_addr"))):
        try:
            with open(path) as fh:
                addr = fh.read().strip()
        except OSError:
            continue
        if addr:
            out.append(addr)
    return out


def router_addrs(fleet_dir: str) -> List[str]:
    """The active router's published address (``<fleet_dir>/
    router_addr``), as a rotation list — [] when no router ever bound."""
    try:
        with open(os.path.join(fleet_dir, "router_addr")) as fh:
            addr = fh.read().strip()
    except OSError:
        return []
    return [addr] if addr else []


def degraded_result(fleet_dir: str, job_id: str,
                    timeout: Optional[float] = 10.0,
                    auth_token: Optional[str] = None) -> dict:
    """``result`` fan-out across the replicas: the first durable record
    wins; otherwise ``pending`` (some replica reachable, none finished)
    or a structured ``no_replicas`` error."""
    reached = False
    for addr in fleet_addrs(fleet_dir):
        try:
            ev = result(addr, job_id, timeout=timeout,
                        auth_token=auth_token)
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            continue
        reached = True
        if ev.get("event") not in ("pending", "error"):
            return dict(ev, degraded=True)
    if reached:
        return {"event": "pending", "job_id": job_id, "degraded": True}
    return {"event": "error", "error": "no_replicas", "degraded": True,
            "detail": f"no replica reachable via {fleet_dir}"}


def degraded_query(fleet_dir: str, q: str, job_id: Optional[str] = None,
                   variant: Optional[str] = None,
                   gene: Optional[str] = None, k: Optional[int] = None,
                   timeout: Optional[float] = 10.0,
                   auth_token: Optional[str] = None) -> dict:
    """Read-plane query fan-out: first replica that answers without an
    error serves it (only the bundle's home replica has the inventory,
    the rest answer ``not_found``)."""
    last: Optional[dict] = None
    for addr in fleet_addrs(fleet_dir):
        try:
            ev = query(addr, q, job_id=job_id, variant=variant,
                       gene=gene, k=k, timeout=timeout,
                       auth_token=auth_token)
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            continue
        if not ev.get("error"):
            return dict(ev, degraded=True)
        last = ev
    if last is not None:
        return dict(last, degraded=True)
    return {"event": "error", "error": "no_replicas", "degraded": True,
            "detail": f"no replica reachable via {fleet_dir}"}


def degraded_status(fleet_dir: str,
                    timeout: Optional[float] = 5.0) -> dict:
    """Per-replica status roll-up assembled client-side — the degraded
    twin of the router's ``status`` aggregate."""
    reps = {}
    for addr in fleet_addrs(fleet_dir):
        try:
            reps[addr] = status(addr, timeout=timeout)
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            reps[addr] = {"event": "error", "error": "unreachable"}
    return {"event": "status", "role": "degraded_client",
            "degraded": True, "fleet_dir": fleet_dir, "replicas": reps}


def degraded_submit(fleet_dir: str, job: dict, tenant: str = "default",
                    idem_key: Optional[str] = None,
                    timeout: Optional[float] = None,
                    priority: Optional[str] = None,
                    deadline_s: Optional[float] = None,
                    auth_token: Optional[str] = None) -> List[dict]:
    """Keyed submit straight to a replica while no router answers.

    Requires an ``idem_key`` (minted when absent — the caller should
    keep it for retries): the key derives the job_id, so this submit is
    reconcilable no matter where it lands. Before submitting, every
    reachable replica is asked for the durable record — a job that
    already ran anywhere dedups client-side. The target replica is
    chosen deterministically from the key over the reachable set, so a
    degraded retry of the same key lands on the same replica and its
    dedup table absorbs it. Raises :class:`ServeConnectionLost` when no
    replica is reachable at all."""
    if idem_key is None:
        idem_key = f"d-{uuid.uuid4().hex}"
    jid = protocol.idem_job_id(idem_key)
    rec = degraded_result(fleet_dir, jid, auth_token=auth_token)
    if rec.get("event") not in ("pending", "error"):
        return [{"event": "accepted", "job_id": jid, "deduped": True,
                 "degraded": True}, rec]
    addrs = fleet_addrs(fleet_dir)
    if not addrs:
        raise ServeConnectionLost(
            f"degraded submit: no replica published an address under "
            f"{fleet_dir}", job_id=jid)
    # Deterministic placement over the *reachable* set: stable for
    # retries of the same key, no coordination required.
    target = addrs[zlib.crc32(idem_key.encode()) % len(addrs)]
    events = submit_job(target, job, tenant=tenant, timeout=timeout,
                        priority=priority, deadline_s=deadline_s,
                        idem_key=idem_key, auth_token=auth_token)
    return [dict(ev, degraded=True) for ev in events]
