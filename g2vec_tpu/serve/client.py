"""Client for the ``g2vec serve`` daemon (CLI, bench, and test currency).

Talks the protocol.py JSONL dialect over the daemon's UNIX socket. The
one failure mode worth a dedicated type: the daemon dying mid-job
(SIGKILL, preemption) closes the stream without a terminal event —
:class:`ServeConnectionLost` carries the job_id so the caller can fall
back to :func:`poll_result`, which reads the result record the RELAUNCHED
daemon writes after the journal re-queues the job.
"""
from __future__ import annotations

import json
import os
import socket
import time
from typing import Iterator, List, Optional

from g2vec_tpu.serve import protocol


class ServeConnectionLost(RuntimeError):
    """The daemon's stream closed before the job's terminal event."""

    def __init__(self, msg: str, job_id: Optional[str] = None):
        super().__init__(msg)
        self.job_id = job_id


def request(socket_path: str, payload: dict,
            timeout: Optional[float] = None) -> Iterator[dict]:
    """Send one request; yield the daemon's JSONL events until it closes
    the stream. ``timeout`` bounds each socket read, not the whole job."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(socket_path)
        f = s.makefile("rwb")
        protocol.write_event(f, payload)
        while True:
            ev = protocol.read_event(f)
            if ev is None:
                return
            yield ev
    finally:
        s.close()


_TERMINAL = ("job_done", "job_failed")


def submit_job(socket_path: str, job: dict, tenant: str = "default",
               timeout: Optional[float] = None) -> List[dict]:
    """Submit ``job`` and stream its events to completion. Returns every
    event received ([..., job_done|job_failed] on success/failure, or
    [rejected] on admission refusal). Raises :class:`ServeConnectionLost`
    if the stream dies first (daemon killed mid-job — poll_result picks
    the job back up after the supervisor relaunch)."""
    events: List[dict] = []
    job_id = None
    for ev in request(socket_path,
                      {"op": "submit", "tenant": tenant, "job": job},
                      timeout=timeout):
        events.append(ev)
        kind = ev.get("event")
        if kind == "accepted":
            job_id = ev.get("job_id")
        if kind == "rejected" or kind in _TERMINAL:
            return events
    raise ServeConnectionLost(
        f"daemon stream closed before job "
        f"{job_id or '<unacknowledged>'} finished", job_id=job_id)


def _one(socket_path: str, op: str, timeout: Optional[float]) -> dict:
    for ev in request(socket_path, {"op": op}, timeout=timeout):
        return ev
    raise ServeConnectionLost(f"no response to {op!r}")


def status(socket_path: str, timeout: Optional[float] = 10.0) -> dict:
    return _one(socket_path, "status", timeout)


def ping(socket_path: str, timeout: Optional[float] = 5.0) -> dict:
    return _one(socket_path, "ping", timeout)


def shutdown(socket_path: str, timeout: Optional[float] = 10.0) -> dict:
    return _one(socket_path, "shutdown", timeout)


def wait_ready(socket_path: str, deadline_s: float = 60.0,
               interval: float = 0.2) -> bool:
    """Poll until the daemon answers ``ping`` (socket may not exist yet
    during startup). True when ready, False at the deadline."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            if ping(socket_path).get("event") == "pong":
                return True
        except (OSError, ServeConnectionLost, protocol.ProtocolError):
            pass
        time.sleep(interval)
    return False


def poll_result(state_dir: str, job_id: str, deadline_s: float = 300.0,
                interval: float = 0.25) -> dict:
    """Wait for ``<state_dir>/results/<job_id>.json`` — the durable
    terminal record, written even when no client is connected (and the
    recovery path after :class:`ServeConnectionLost`)."""
    path = os.path.join(state_dir, "results", f"{job_id}.json")
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass        # mid-write; atomic rename makes this brief
        time.sleep(interval)
    raise TimeoutError(f"no result record for job {job_id} within "
                       f"{deadline_s:.0f}s ({path})")
