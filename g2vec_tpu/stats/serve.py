"""Scenario execution over a serve fleet: one job per replicate,
one idempotency key each.

A long scenario on the lane path dies with its process. Submitted
through serve, every replicate is a separate durable job whose id is
``idem_job_id("scn-<scenario_id>-<name>")`` — deterministic, so after a
daemon SIGKILL, a drain, or replica failover the client simply
resubmits: replicates that already ran dedup to their existing result
record (exactly-once), replicates in flight resume from their
checkpoints, and the final stability artifact is byte-identical to the
lane-path run of the same plan (both paths share reduce_scenario and
the solo-parity contract).

This module is pure client + reducer: the daemon needs no scenario
concept. Replicate variants ride the existing manifest schema inside
each job dict, and the reducer reads biomarker lists back from the
``variants`` map of the durable result records.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from g2vec_tpu.config import G2VecConfig, config_from_job
from g2vec_tpu.stats.plan import (expand_plan, plan_from_config,
                                  scenario_variants)
from g2vec_tpu.stats.run import ScenarioResult, write_scenario_artifact
from g2vec_tpu.utils.metrics import MetricsWriter


def _load_reduction_dataset(cfg: G2VecConfig):
    """The preprocessed full-cohort dataset, mirrored step for step from
    ResidentEngine.dataset — the reducer runs client-side, possibly on a
    machine that is not a serve replica, so it loads its own copy."""
    from g2vec_tpu.io.readers import (load_clinical, load_expression,
                                      load_network)
    from g2vec_tpu.preprocess import (find_common_genes, match_labels,
                                      restrict_data)

    data = load_expression(cfg.expression_file,
                           use_native=cfg.use_native_io)
    clinical = load_clinical(cfg.clinical_file)
    network = load_network(cfg.network_file)
    data.label = match_labels(clinical, data.sample)
    common = find_common_genes(network.genes, data.gene)
    return restrict_data(data, common)


def _read_biomarkers(path: str) -> List[str]:
    with open(path) as f:
        lines = [ln.rstrip("\n") for ln in f]
    if not lines or lines[0] != "GeneSymbol":
        raise ValueError(f"{path}: not a biomarkers file")
    return [ln for ln in lines[1:] if ln]


def run_scenario_serve(socket_path: str, base_job: dict, *,
                       scenario: str, replicates: int = 0, folds: int = 0,
                       scenario_seed: int = 0, state_dir: str,
                       tenant: str = "default",
                       timeout: Optional[float] = 10.0,
                       poll_deadline_s: float = 300.0, retries: int = 3,
                       priority: Optional[str] = None,
                       deadline_s: Optional[float] = None,
                       auth_token: Optional[str] = None,
                       metrics_jsonl: Optional[str] = None,
                       console: Callable[[str], None] = print
                       ) -> ScenarioResult:
    """Run a scenario as per-replicate serve jobs and reduce locally.

    ``base_job`` is an ordinary serve job dict (SERVE_JOB_KEYS only —
    the scenario axes are passed explicitly and expanded client-side).
    Submission is sequential and restart-safe: each replicate's
    idempotency key is a pure function of the scenario id and replicate
    name, so calling this function again after any failure re-converges
    on the same jobs and the same artifact.
    """
    from g2vec_tpu.serve import client

    import dataclasses as _dc

    cfg = config_from_job(dict(base_job))
    cfg = _dc.replace(cfg, scenario=scenario, replicates=replicates,
                      folds=folds, scenario_seed=scenario_seed)
    cfg.validate()
    plan = plan_from_config(cfg)
    # Validate the full expansion up front through the engine's manifest
    # validator (errors name "scenario <id>, replicate <i>") before any
    # job reaches the fleet.
    sid, variants = scenario_variants(plan, cfg)
    metrics = MetricsWriter(metrics_jsonl)
    try:
        ev = {"scenario": plan.scenario, "scenario_id": sid,
              "scenario_seed": plan.scenario_seed,
              "n_variants": len(variants), "via": "serve"}
        if plan.scenario == "cv":
            ev["folds"] = plan.folds
        else:
            ev["replicates"] = plan.replicates
        metrics.emit("scenario", **ev)
        console(f"scenario {plan.scenario} ({sid}): {len(variants)} "
                f"replicate jobs via {socket_path}")
        lists_by_name: Dict[str, List[str]] = {}
        for i, (obj, origin) in enumerate(expand_plan(plan, cfg)):
            name = obj["name"]
            job = dict(base_job)
            job.pop("seeds", None)
            job["variants"] = [obj]
            idem = f"scn-{sid}-{name}"
            try:
                rec = client.submit_and_wait(
                    socket_path, job, tenant=tenant, state_dir=state_dir,
                    timeout=timeout, poll_deadline_s=poll_deadline_s,
                    retries=retries, priority=priority,
                    deadline_s=deadline_s, idem_key=idem,
                    auth_token=auth_token)
            except Exception as exc:
                raise RuntimeError(
                    f"scenario {sid}, {origin}: {exc}") from exc
            if rec.get("status") != "done":
                raise RuntimeError(
                    f"scenario {sid}, {origin}: job {rec.get('job_id')} "
                    f"ended with {rec.get('event')}")
            vrec = rec["variants"][name]
            bio_paths = [p for p in vrec["outputs"]
                         if p.endswith("_biomarkers.txt")]
            if len(bio_paths) != 1:
                raise RuntimeError(
                    f"scenario {sid}, {origin}: expected one biomarkers "
                    f"output, got {vrec['outputs']}")
            lists_by_name[name] = _read_biomarkers(bio_paths[0])
            metrics.emit("replicate", name=name, index=i,
                         n_selected=len(set(lists_by_name[name])),
                         acc_val=float(vrec.get("acc_val") or 0.0))
            console(f"scenario {sid}: {origin} done "
                    f"({len(lists_by_name[name])} biomarker lines)")
        data = _load_reduction_dataset(cfg)
        path, columns, extras = write_scenario_artifact(
            plan, sid, cfg, data, variants, lists_by_name, metrics)
        console(f"scenario {sid}: wrote {path}")
        return ScenarioResult(scenario=plan.scenario, scenario_id=sid,
                              output=path, columns=columns,
                              n_variants=len(variants), extras=extras,
                              walk_stats={})
    finally:
        metrics.close()
