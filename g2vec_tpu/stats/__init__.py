"""Statistical scenario engine — one biomarker run into a defensible study.

The paper's claim is a gene RANKING from one run; its evidential weight
is how stable that ranking is under patient resampling, how it compares
to a label-shuffled null, and how well it prognoses held-out patients.
This package turns those three protocols into first-class runs:

- ``plan.py``    — a :class:`ScenarioPlan` expands ``--scenario
  bootstrap|permutation|cv`` into a seeded variant manifest (the seed
  derivation tree makes every replicate a pure function of
  ``--scenario-seed``);
- ``run.py``     — executes the manifest as shape-bucketed lanes on the
  resident batch engine (batch/engine.py), so replicates amortize
  stages 1-2, walk products, and compiles exactly like any manifest;
- ``serve.py``   — or submits one serve job per replicate with a
  deterministic idempotency key each, so a long scenario survives
  daemon SIGKILL/drain/replica failover with exactly-once accounting;
- ``reduce.py``  — pure-numpy reducers folding per-replicate outputs
  into ``<NAME>_stability.txt`` (io/writers.write_stability).

Every sampled replicate is byte-identical to its solo twin run
(``lane_config`` + the PR 5 parity contract), and a permutation scenario
walks each (cohort, group) product exactly once — null lanes differ only
in the stage-6 label view, so they all share one walk product through
the SharedWalkTier.
"""
from g2vec_tpu.stats.plan import (ScenarioPlan, derive_seed,  # noqa: F401
                                  expand_plan, plan_from_config,
                                  scenario_id, scenario_variants)
