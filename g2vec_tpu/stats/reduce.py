"""Scenario reducers: per-replicate outputs → one stability table.

Everything here is host-side, pure numpy, float64 — reduction runs once
over tiny per-replicate artifacts (gene lists, label vectors) and its
job is to be exactly reproducible, not fast. Each ``reduce_*`` returns
``(columns, rows, extras)`` where every cell in ``rows`` is already a
string ("%.6f" floats, "%d" counts, "na" sentinels): the reducer owns
formatting so ``write_stability`` is a byte concatenator and the
artifact is deterministic by construction.

Statistical choices, pinned here because tests assert them:

- permutation p-values use the add-one estimator
  ``p = (1 + #{r: t_null >= t_obs}) / (1 + R)`` — never 0, and a gene
  whose expression is constant (t = 0 everywhere, all ties) gets p = 1;
- BH-FDR q-values are the reversed running minimum of ``p * m / rank``
  over the stable p-ordering, capped at 1;
- a replicate's "rank" for a gene is the 1-based position of its FIRST
  line in that replicate's biomarker file (the file is a sorted union of
  two L-group blocks, so a gene can appear twice — it counts once);
- rank variance uses ddof=0 over the replicates that selected the gene.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def np_tscores(expr_good: np.ndarray, expr_poor: np.ndarray) -> np.ndarray:
    """Float64 host twin of ops/stats.tscores (absolute pooled-variance
    t per gene), mirrored term for term so the observed statistic and
    the permutation nulls come from one formula."""
    g = np.asarray(expr_good, dtype=np.float64)
    p = np.asarray(expr_poor, dtype=np.float64)
    n0, n1 = g.shape[0], p.shape[0]
    m0, m1 = g.mean(axis=0), p.mean(axis=0)
    s0, s1 = g.std(axis=0, ddof=1), p.std(axis=0, ddof=1)
    pooled = ((n0 - 1) * s0 ** 2 + (n1 - 1) * s1 ** 2) / (n0 + n1 - 2)
    d1 = np.sqrt(pooled)
    d2 = np.sqrt(1.0 / n0 + 1.0 / n1)
    ok = (d1 > 0) & (d2 > 0)
    t = np.where(ok, (m0 - m1) / np.where(ok, d1, 1.0) / d2, 0.0)
    return np.abs(t)


def perm_pvalues(t_obs: np.ndarray, t_null: np.ndarray) -> np.ndarray:
    """Add-one permutation p per gene. ``t_null`` is [R, G]."""
    t_obs = np.asarray(t_obs, dtype=np.float64)
    t_null = np.asarray(t_null, dtype=np.float64)
    if t_null.ndim != 2 or t_null.shape[1] != t_obs.shape[0]:
        raise ValueError(f"perm_pvalues: null shape {t_null.shape} vs "
                         f"{t_obs.shape[0]} observed scores")
    ge = (t_null >= t_obs[None, :]).sum(axis=0)
    return (1.0 + ge) / (1.0 + t_null.shape[0])


def bh_fdr(pvalues: np.ndarray) -> np.ndarray:
    """Benjamini-Hochberg q-values (stable ordering, capped at 1)."""
    p = np.asarray(pvalues, dtype=np.float64)
    m = p.shape[0]
    order = np.argsort(p, kind="stable")
    ranked = p[order] * m / np.arange(1, m + 1)
    ranked = np.minimum(np.minimum.accumulate(ranked[::-1])[::-1], 1.0)
    q = np.empty(m, dtype=np.float64)
    q[order] = ranked
    return q


def selection_stats(genes: Sequence[str],
                    replicate_lists: Sequence[Sequence[str]]
                    ) -> Dict[str, np.ndarray]:
    """Per-gene selection frequency and rank dispersion across
    replicate biomarker lists (file order = rank order)."""
    n_rep = len(replicate_lists)
    if n_rep == 0:
        raise ValueError("selection_stats: no replicate lists")
    pos = {g: i for i, g in enumerate(genes)}
    n_sel = np.zeros(len(genes), dtype=np.int64)
    ranks: List[List[int]] = [[] for _ in genes]
    for rep in replicate_lists:
        seen = set()
        for rank, gene in enumerate(rep, start=1):
            if gene in seen:
                continue  # duplicate line (gene topped both L-groups)
            seen.add(gene)
            gi = pos.get(gene)
            if gi is None:
                raise ValueError(
                    f"selection_stats: replicate selected unknown gene "
                    f"{gene!r}")
            n_sel[gi] += 1
            ranks[gi].append(rank)
    mean_rank = np.full(len(genes), np.nan)
    rank_var = np.full(len(genes), np.nan)
    for gi, r in enumerate(ranks):
        if r:
            arr = np.asarray(r, dtype=np.float64)
            mean_rank[gi] = arr.mean()
            rank_var[gi] = arr.var(ddof=0)
    return {"n_sel": n_sel, "sel_freq": n_sel / float(n_rep),
            "mean_rank": mean_rank, "rank_var": rank_var}


def percentile_ci(values: Sequence[float], lo: float = 2.5,
                  hi: float = 97.5) -> Tuple[float, float]:
    arr = np.asarray(values, dtype=np.float64)
    if arr.size == 0:
        raise ValueError("percentile_ci: no values")
    return (float(np.percentile(arr, lo)), float(np.percentile(arr, hi)))


def centroid_accuracy(train_x: np.ndarray, train_y: np.ndarray,
                      test_x: np.ndarray, test_y: np.ndarray) -> float:
    """Held-out prognostic accuracy of the nearest-class-centroid rule
    over the replicate's biomarker columns. Deterministic: float64
    throughout, distance ties resolve to class 0."""
    tx = np.asarray(train_x, dtype=np.float64)
    ty = np.asarray(train_y)
    ex = np.asarray(test_x, dtype=np.float64)
    ey = np.asarray(test_y)
    if not (ty == 0).any() or not (ty == 1).any():
        raise ValueError("centroid_accuracy: training fold lost a class")
    c0 = tx[ty == 0].mean(axis=0)
    c1 = tx[ty == 1].mean(axis=0)
    d0 = ((ex - c0[None, :]) ** 2).sum(axis=1)
    d1 = ((ex - c1[None, :]) ** 2).sum(axis=1)
    pred = (d1 < d0).astype(ey.dtype)
    return float((pred == ey).mean())


def _f(x: float) -> str:
    return "%.6f" % x


def _na_f(x: float) -> str:
    return "na" if np.isnan(x) else _f(x)


def reduce_selection(genes: Sequence[str],
                     replicate_lists: Sequence[Sequence[str]]
                     ) -> Tuple[List[str], List[List[str]], Dict]:
    """Bootstrap (and CV selection-side) reduction: how often and how
    stably each gene makes the biomarker list."""
    stats = selection_stats(genes, replicate_lists)
    columns = ["sel_freq", "n_sel", "mean_rank", "rank_var"]
    rows = [[_f(stats["sel_freq"][i]), "%d" % stats["n_sel"][i],
             _na_f(stats["mean_rank"][i]), _na_f(stats["rank_var"][i])]
            for i in range(len(genes))]
    return columns, rows, {"n_replicates": len(replicate_lists)}


def reduce_permutation(genes: Sequence[str], t_obs: np.ndarray,
                       t_null: np.ndarray,
                       observed_biomarkers: Sequence[str]
                       ) -> Tuple[List[str], List[List[str]], Dict]:
    """Permutation reduction: observed |t| vs the label-shuffled null,
    with BH-FDR q-values and the observed selection as context."""
    p = perm_pvalues(t_obs, t_null)
    q = bh_fdr(p)
    selected = set(observed_biomarkers)
    columns = ["t_obs", "p_value", "q_value", "selected_obs"]
    rows = [[_f(t_obs[i]), _f(p[i]), _f(q[i]),
             "1" if genes[i] in selected else "0"]
            for i in range(len(genes))]
    return columns, rows, {"n_replicates": int(t_null.shape[0])}


def reduce_cv(genes: Sequence[str],
              fold_lists: Sequence[Sequence[str]],
              fold_accuracies: Sequence[float]
              ) -> Tuple[List[str], List[List[str]], Dict]:
    """CV reduction: selection stability across folds plus the held-out
    accuracy distribution (mean and percentile CI) in the extras."""
    columns, rows, extras = reduce_selection(genes, fold_lists)
    acc = np.asarray(fold_accuracies, dtype=np.float64)
    ci_lo, ci_hi = percentile_ci(acc)
    extras.update(acc_mean=float(acc.mean()), ci_lo=ci_lo, ci_hi=ci_hi,
                  fold_acc=[_f(a) for a in acc])
    return columns, rows, extras
