"""Scenario planning: ``--scenario`` → a seeded, deterministic manifest.

A :class:`ScenarioPlan` is the tiny declarative core of the engine: the
protocol name plus its size and a root seed. Everything downstream — per
replicate subsample seeds, permutation draws, the fold partition — is
derived from ``scenario_seed`` through one hash tree (:func:`derive_seed`),
so a scenario is a pure function of its plan: rerunning with the same
plan and inputs reproduces every replicate byte for byte, and any single
replicate can be reproduced solo by copying its variant dict into a
one-entry manifest (the solo-twin contract tested in test_scenario.py).

Expansion targets the existing manifest schema (batch/engine.py
``_variant_from_dict``): a scenario IS a generated manifest, which is why
``--scenario`` is mutually exclusive with ``--manifest``/``--seeds`` and
why both the lane path (stats/run.py) and the serve path (stats/serve.py)
can execute the same variants unchanged.
"""
from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Tuple

from g2vec_tpu.config import G2VecConfig

# Config axes that change the numeric content of a replicate's outputs.
# scenario_id hashes these plus the plan plus the input file BASENAMES —
# never result_name or directories, so a rerun into a different output
# directory keeps the same id and a byte-identical stability artifact.
_ID_FIELDS = ("lenPath", "numRepetition", "sizeHiddenlayer", "epoch",
              "learningRate", "numBiomarker", "pcc_threshold", "score_mix",
              "seed", "train_seed", "kmeans_seed", "patient_subsample",
              "subsample_seed", "compute_dtype", "walker_backend")


@dataclass(frozen=True)
class ScenarioPlan:
    scenario: str        # "bootstrap" | "permutation" | "cv"
    replicates: int = 0  # bootstrap/permutation replicate count
    folds: int = 0       # cv fold count
    scenario_seed: int = 0

    @property
    def n_variants(self) -> int:
        if self.scenario == "bootstrap":
            return self.replicates
        if self.scenario == "permutation":
            return self.replicates + 1  # + the observed lane
        return self.folds


def plan_from_config(cfg: G2VecConfig) -> ScenarioPlan:
    if not cfg.scenario:
        raise ValueError("plan_from_config: config has no --scenario")
    return ScenarioPlan(scenario=cfg.scenario, replicates=cfg.replicates,
                        folds=cfg.folds, scenario_seed=cfg.scenario_seed)


def derive_seed(scenario_seed: int, index: int, role: str) -> int:
    """One node of the scenario seed tree: a stable 31-bit seed per
    (root, role, index). SHA-256 so adjacent indices are uncorrelated
    and the tree is identical across platforms/processes."""
    digest = hashlib.sha256(
        f"g2vec-scenario:{scenario_seed}:{role}:{index}".encode()).digest()
    return int.from_bytes(digest[:4], "big") & 0x7FFFFFFF


def scenario_id(plan: ScenarioPlan, cfg: G2VecConfig) -> str:
    """12-hex fingerprint naming this scenario in artifacts, metrics
    events, and serve idempotency keys (``scn-<id>-<replicate>`` — the
    key that makes daemon-restart resubmission dedup to exactly-once)."""
    payload = {
        "scenario": plan.scenario,
        "replicates": plan.replicates,
        "folds": plan.folds,
        "scenario_seed": plan.scenario_seed,
        "inputs": [os.path.basename(cfg.expression_file),
                   os.path.basename(cfg.clinical_file),
                   os.path.basename(cfg.network_file)],
        "config": {k: getattr(cfg, k) for k in _ID_FIELDS},
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def expand_plan(plan: ScenarioPlan, cfg: G2VecConfig
                ) -> List[Tuple[Dict, str]]:
    """Expand the plan into (variant-dict, origin) pairs in manifest
    order. Variant dicts use the engine's manifest schema verbatim;
    ``origin`` is the human name threaded into validation errors
    (satellite: errors must name the scenario and replicate).
    """
    out: List[Tuple[Dict, str]] = []
    if plan.scenario == "bootstrap":
        if plan.replicates < 1:
            raise ValueError("bootstrap scenario needs --replicates >= 1")
        frac = cfg.patient_subsample or 1.0
        for r in range(plan.replicates):
            out.append(({"name": "b%03d" % r,
                         "subsample_mode": "bootstrap",
                         "patient_subsample": frac,
                         "subsample_seed": derive_seed(
                             plan.scenario_seed, r, "bootstrap")},
                        "replicate %d" % r))
    elif plan.scenario == "permutation":
        if plan.replicates < 1:
            raise ValueError("permutation scenario needs --replicates >= 1")
        # Lane 0 is the OBSERVED run: same cohort, unshuffled labels. The
        # nulls differ from it only in permute_seed, which is deliberately
        # outside expr_key() — all R+1 lanes share one walk product, so a
        # cold engine walks each (cohort, group) exactly once.
        out.append(({"name": "obs"}, "observed"))
        for r in range(plan.replicates):
            out.append(({"name": "p%03d" % r,
                         "permute_seed": derive_seed(
                             plan.scenario_seed, r, "permutation")},
                        "replicate %d" % r))
    elif plan.scenario == "cv":
        if plan.folds < 2:
            raise ValueError("cv scenario needs --folds >= 2")
        # One shared stratified partition; fold k's lane trains on the
        # complement of fold k. All folds share the partition seed so the
        # union of held-out sets covers every patient exactly once.
        part_seed = derive_seed(plan.scenario_seed, 0, "folds")
        for k in range(plan.folds):
            out.append(({"name": "f%02d" % k,
                         "subsample_mode": "fold",
                         "cv_folds": plan.folds,
                         "cv_fold": k,
                         "subsample_seed": part_seed},
                        "fold %d" % k))
    else:
        raise ValueError(f"unknown scenario {plan.scenario!r}")
    return out


def scenario_variants(plan: ScenarioPlan, cfg: G2VecConfig):
    """Expand and validate through the engine's own manifest validator,
    so scenario-generated variants obey exactly the constraints a
    hand-written manifest would — with errors that name their origin."""
    from g2vec_tpu.batch.engine import _variant_from_dict

    sid = scenario_id(plan, cfg)
    variants = []
    for i, (obj, origin) in enumerate(expand_plan(plan, cfg)):
        variants.append(_variant_from_dict(
            i, obj, cfg, origin=f"scenario {sid}, {origin}"))
    return sid, variants
