"""Scenario execution on the lane substrate, plus the shared reducer.

``run_scenario`` is what ``--scenario`` dispatches to: plan → expand →
execute the variants as ONE batch on an ephemeral ResidentEngine (so
replicates share stages 1-2, walk products, and compiled programs like
any manifest) → reduce the per-lane biomarker lists into
``<NAME>_stability.txt``.

The reduction half (:func:`reduce_scenario` /
:func:`write_scenario_artifact`) is deliberately execution-agnostic — it
consumes (variant, biomarker-list) pairs and the preprocessed dataset,
so stats/serve.py reuses it unchanged on result records fetched from a
serve fleet. One reducer, two substrates, one artifact byte format.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.io.writers import write_stability
from g2vec_tpu.stats import reduce as red
from g2vec_tpu.stats.plan import (ScenarioPlan, derive_seed,
                                  plan_from_config, scenario_variants)
from g2vec_tpu.utils.metrics import MetricsWriter


@dataclasses.dataclass
class ScenarioResult:
    scenario: str
    scenario_id: str
    output: str                   # path of <NAME>_stability.txt
    columns: List[str]
    n_variants: int
    extras: Dict                  # reducer extras (n_replicates, acc_*…)
    walk_stats: Dict[str, int]    # engine walk accounting ({} on serve)


def reduce_scenario(plan: ScenarioPlan, cfg: G2VecConfig, data,
                    variants: Sequence,
                    lists_by_name: Dict[str, List[str]]
                    ) -> Tuple[List[str], List[str], List[List[str]], Dict]:
    """Fold per-replicate biomarker lists into the stability table.

    ``data`` is the preprocessed full-cohort dataset (engine.dataset's
    ``bundle["data"]``); ``variants`` the plan's LaneVariants in manifest
    order; ``lists_by_name`` maps variant name → that replicate's
    biomarker file lines. Returns (genes, columns, rows, extras).
    """
    genes = [str(g) for g in data.gene]
    if plan.scenario == "bootstrap":
        columns, rows, extras = red.reduce_selection(
            genes, [lists_by_name[v.name] for v in variants])
    elif plan.scenario == "permutation":
        from g2vec_tpu.batch.engine import _lane_cohort
        from g2vec_tpu.preprocess import permute_labels

        # The null t-statistics are recomputed host-side from the SAME
        # cohort and permute seeds the lanes scored under — the reducer
        # needs the full [R, G] null table, not just selections.
        obs, nulls = variants[0], variants[1:]
        cohort = data if obs.expr_key() is None else _lane_cohort(data, obs)
        labels = np.asarray(cohort.label)
        expr = np.asarray(cohort.expr)
        t_obs = red.np_tscores(expr[labels == 0], expr[labels == 1])
        t_null = np.stack([red.np_tscores(expr[pl == 0], expr[pl == 1])
                           for pl in (permute_labels(labels, v.permute_seed)
                                      for v in nulls)])
        columns, rows, extras = red.reduce_permutation(
            genes, t_obs, t_null, lists_by_name[obs.name])
    elif plan.scenario == "cv":
        from g2vec_tpu.preprocess import fold_assignments

        labels = np.asarray(data.label)
        folds = fold_assignments(labels, plan.folds,
                                 derive_seed(plan.scenario_seed, 0, "folds"))
        gene_pos = {g: i for i, g in enumerate(genes)}
        expr = np.asarray(data.expr, dtype=np.float64)
        accs = []
        for k, v in enumerate(variants):
            sel, seen = [], set()
            for g in lists_by_name[v.name]:
                if g not in seen:
                    seen.add(g)
                    sel.append(gene_pos[g])
            cols = np.asarray(sel, dtype=np.int64)
            train, test = folds != k, folds == k
            accs.append(red.centroid_accuracy(
                expr[train][:, cols], labels[train],
                expr[test][:, cols], labels[test]))
        columns, rows, extras = red.reduce_cv(
            genes, [lists_by_name[v.name] for v in variants], accs)
    else:
        raise ValueError(f"unknown scenario {plan.scenario!r}")
    return genes, columns, rows, extras


def write_scenario_artifact(plan: ScenarioPlan, sid: str,
                            cfg: G2VecConfig, data, variants: Sequence,
                            lists_by_name: Dict[str, List[str]],
                            metrics: Optional[MetricsWriter] = None
                            ) -> Tuple[str, List[str], Dict]:
    """Reduce + render + write ``<NAME>_stability.txt`` and emit the
    ``stability`` event. Meta lines carry only run-identity (never
    paths), so reruns into different directories stay byte-identical."""
    genes, columns, rows, extras = reduce_scenario(
        plan, cfg, data, variants, lists_by_name)
    meta: List[Tuple[str, object]] = [
        ("scenario_id", sid), ("scenario_seed", plan.scenario_seed),
        ("n_variants", len(variants))]
    if plan.scenario == "cv":
        meta.append(("folds", plan.folds))
        meta.append(("acc_mean", "%.6f" % extras["acc_mean"]))
        meta.append(("acc_ci95", "%.6f,%.6f" % (extras["ci_lo"],
                                                extras["ci_hi"])))
        meta.append(("fold_acc", ",".join(extras["fold_acc"])))
    else:
        meta.append(("replicates", plan.replicates))
    path = write_stability(cfg.result_name, plan.scenario, meta, columns,
                           genes, rows)
    if metrics is not None:
        ev = {"scenario_id": sid, "output": path, "n_genes": len(genes),
              "columns": columns, "n_replicates": extras["n_replicates"]}
        if plan.scenario == "cv":
            ev.update(acc_mean=extras["acc_mean"], ci_lo=extras["ci_lo"],
                      ci_hi=extras["ci_hi"])
        metrics.emit("stability", **ev)
    return path, columns, extras


def run_scenario(cfg: G2VecConfig,
                 console: Callable[[str], None] = print,
                 check: Optional[Callable[[], None]] = None
                 ) -> ScenarioResult:
    """Execute ``cfg``'s scenario end to end on the batch engine."""
    from g2vec_tpu.batch.engine import ResidentEngine

    cfg.validate()
    plan = plan_from_config(cfg)
    sid, variants = scenario_variants(plan, cfg)
    metrics = MetricsWriter(cfg.metrics_jsonl)
    try:
        ev = {"scenario": plan.scenario, "scenario_id": sid,
              "scenario_seed": plan.scenario_seed,
              "n_variants": len(variants), "via": "lanes"}
        if plan.scenario == "cv":
            ev["folds"] = plan.folds
        else:
            ev["replicates"] = plan.replicates
        metrics.emit("scenario", **ev)
        console(f"scenario {plan.scenario} ({sid}): "
                f"{len(variants)} variants as one lane batch")
        with ResidentEngine(cache_dir=cfg.cache_dir,
                            compilation_cache=cfg.compilation_cache,
                            walk_cache=cfg.walk_cache) as engine:
            batch = engine.execute(cfg, variants, console=console,
                                   metrics=metrics, check=check)
            bundle, _ = engine.dataset(cfg)
        lists_by_name = {}
        for i, (v, lane) in enumerate(zip(batch.variants, batch.lanes)):
            lists_by_name[v.name] = list(lane.biomarkers)
            metrics.emit("replicate", name=v.name, index=i,
                         n_selected=len(set(lane.biomarkers)),
                         acc_val=float(lane.acc_val))
        path, columns, extras = write_scenario_artifact(
            plan, sid, cfg, bundle["data"], batch.variants, lists_by_name,
            metrics)
        console(f"scenario {sid}: wrote {path} "
                f"(walked={batch.walk_stats.get('walked', 0)}, "
                f"memo_hits={batch.walk_stats.get('memo_hits', 0)})")
        return ScenarioResult(scenario=plan.scenario, scenario_id=sid,
                              output=path, columns=columns,
                              n_variants=len(variants), extras=extras,
                              walk_stats=dict(batch.walk_stats))
    finally:
        metrics.close()
