"""L6 — output writers, byte-identical to the reference formats.

- ``<NAME>_biomarkers.txt`` (ref: G2Vec.py:127-131): header ``GeneSymbol``
  then one gene symbol per line.
- ``<NAME>_lgroups.txt`` (ref: G2Vec.py:159-165): header
  ``GeneSymbol\\tLgroup(0:good,1:poor,2:other)`` then ``gene\\t<int>`` for ALL
  genes in global (sorted-intersection) order.
- ``<NAME>_vectors.txt`` (ref: G2Vec.py:203-215): header
  ``GeneSymbol\\tV0...V{h-1}`` then ``gene`` + ``\\t%.6f`` per dim for ALL genes.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np


def write_biomarkers(result_name: str, biomarkers: Sequence[str]) -> str:
    path = result_name + "_biomarkers.txt"
    with open(path, "w") as fout:
        fout.write("GeneSymbol\n")
        for gene in biomarkers:
            fout.write("%s\n" % gene)
    return path


def write_lgroups(result_name: str, lgroup_idx: np.ndarray,
                  genes: Sequence[str]) -> str:
    if len(genes) != len(lgroup_idx):
        raise ValueError(f"write_lgroups: {len(genes)} genes vs "
                         f"{len(lgroup_idx)} L-group indices")
    path = result_name + "_lgroups.txt"
    with open(path, "w") as fout:
        fout.write("GeneSymbol\tLgroup(0:good,1:poor,2:other)\n")
        for gene, group in zip(genes, lgroup_idx):
            fout.write("%s\t%d\n" % (gene, group))
    return path


def write_vectors(result_name: str, vectors: np.ndarray,
                  genes: Sequence[str]) -> str:
    path = result_name + "_vectors.txt"
    vectors = np.asarray(vectors, dtype=np.float32)
    if len(genes) != vectors.shape[0]:
        raise ValueError(f"write_vectors: {len(genes)} genes vs "
                         f"{vectors.shape[0]} embedding rows")
    with open(path, "w") as fout:
        fout.write("GeneSymbol")
        for i in range(vectors.shape[1]):
            fout.write("\tV%d" % i)
        fout.write("\n")
        for gene, vector in zip(genes, vectors):
            fout.write(gene)
            for val in vector:
                fout.write("\t%.6f" % val)
            fout.write("\n")
    return path
