"""L6 — output writers, byte-identical to the reference formats.

- ``<NAME>_biomarkers.txt`` (ref: G2Vec.py:127-131): header ``GeneSymbol``
  then one gene symbol per line.
- ``<NAME>_lgroups.txt`` (ref: G2Vec.py:159-165): header
  ``GeneSymbol\\tLgroup(0:good,1:poor,2:other)`` then ``gene\\t<int>`` for ALL
  genes in global (sorted-intersection) order.
- ``<NAME>_vectors.txt`` (ref: G2Vec.py:203-215): header
  ``GeneSymbol\\tV0...V{h-1}`` then ``gene`` + ``\\t%.6f`` per dim for ALL genes.
- ``<NAME>_stability.txt`` (new — stats/): ``#``-prefixed scenario metadata
  lines, a ``GeneSymbol\\t<col>...`` header, then one preformatted row per
  gene in global order (the reducer renders every cell to a string so the
  artifact is byte-deterministic by construction).
- ``<NAME>_inventory/`` (new — the query plane's binary bundle,
  ``--emit-inventory`` solo / published by the serve daemon on job
  completion): float32 ``embeddings.npy`` ``[G, H]`` + precomputed
  ``norms.npy`` row L2 norms + ``scores.npy`` ``[2, G]`` prognostic
  scores + ``genes.txt`` + ``meta.json``, sealed by a sha256
  ``MANIFEST.json`` (utils/integrity). One writer serves both paths, so
  a served bundle's array files are byte-identical to its solo twin's.
"""
from __future__ import annotations

import os
import shutil
from typing import Optional, Sequence

import numpy as np

#: Bundle files whose bytes must match between a solo ``--emit-inventory``
#: run and the serve daemon's publication of the same config (meta.json
#: carries run-context fields — job id, publish source — and is excluded).
INVENTORY_ARRAYS = ("embeddings.npy", "norms.npy", "scores.npy", "genes.txt")
INVENTORY_MANIFEST = "MANIFEST.json"
#: Generation pointer at the bundle ROOT: one line naming the live
#: generation subdirectory (``gen-NNNNNN``). Written tmp + os.replace
#: LAST, so the flip is the atomic commit point — a reader resolves
#: the pointer and maps entirely-old or entirely-new files, never a
#: mix. Bundles written before the generational layout have their
#: files flat in the root (no pointer) and stay readable as-is.
GENERATION_FILE = "GENERATION"
#: Top-M prognostic-score shortlist rows per group kept in
#: ``ann_scores.npy`` (int64 ``[2, M]``) — computed with the exact
#: top-k kernel at build time, so serving a k <= M biomarker query
#: from the prefix is identical to the exact answer by construction.
ANN_SCORE_TOPM = 1024


def read_generation(bundle_dir: str) -> str:
    """The live generation subdir name from ``bundle_dir``'s pointer,
    or ``""`` for a pre-generational flat bundle (files in the root)."""
    try:
        with open(os.path.join(bundle_dir, GENERATION_FILE)) as f:
            return f.read().strip()
    except OSError:
        return ""


def _next_generation(bundle_dir: str) -> int:
    """Serial for the next generation: one past the live pointer's, or
    past the highest ``gen-*`` dir present (an orphan from a crash
    between the subdir rename and the pointer flip must not be reused)."""
    serial = 0
    cur = read_generation(bundle_dir)
    if cur.startswith("gen-"):
        try:
            serial = int(cur[4:])
        except ValueError:
            serial = 0
    try:
        names = os.listdir(bundle_dir)
    except OSError:
        names = []
    for name in names:
        if name.startswith("gen-"):
            try:
                serial = max(serial, int(name[4:]))
            except ValueError:
                continue
    return serial + 1


def write_biomarkers(result_name: str, biomarkers: Sequence[str]) -> str:
    path = result_name + "_biomarkers.txt"
    with open(path, "w") as fout:
        fout.write("GeneSymbol\n")
        for gene in biomarkers:
            fout.write("%s\n" % gene)
    return path


def write_lgroups(result_name: str, lgroup_idx: np.ndarray,
                  genes: Sequence[str]) -> str:
    if len(genes) != len(lgroup_idx):
        raise ValueError(f"write_lgroups: {len(genes)} genes vs "
                         f"{len(lgroup_idx)} L-group indices")
    path = result_name + "_lgroups.txt"
    with open(path, "w") as fout:
        fout.write("GeneSymbol\tLgroup(0:good,1:poor,2:other)\n")
        for gene, group in zip(genes, lgroup_idx):
            fout.write("%s\t%d\n" % (gene, group))
    return path


def write_vectors_sharded(result_name: str, vectors_local: np.ndarray,
                          genes: Sequence[str], sctx) -> str:
    """:func:`write_vectors` for a gene-range-sharded embedding
    (ROADMAP item 2): every rank publishes its ``[g_local, H]`` slice
    over the explicit-key chunked transport; rank 0 streams the slices
    into the file IN RANK ORDER — rank order IS gene order (contiguous
    ranges), and the writer holds one slice at a time, never the [G, H]
    table the sharding exists to avoid. The row format is
    :func:`write_vectors`'s own, byte for byte.

    COLLECTIVE over the shard context's ranks: every rank must call
    (non-writers publish and return). ``genes`` is the FULL gene list
    (every rank has it); the path returns on every rank.
    """
    import io as _io

    from g2vec_tpu.parallel import hostcomm

    spec = sctx.spec
    path = result_name + "_vectors.txt"
    vectors_local = np.asarray(vectors_local, dtype=np.float32)
    if spec.n_ranks == 1:
        return write_vectors(result_name, vectors_local, genes)
    lo, hi = spec.gene_range()
    if vectors_local.shape[0] != hi - lo:
        raise ValueError(
            f"write_vectors_sharded: rank {spec.rank} has "
            f"{vectors_local.shape[0]} rows for gene range [{lo}, {hi})")
    buf = _io.BytesIO()
    np.save(buf, vectors_local, allow_pickle=False)
    hostcomm.put_bytes_chunked(f"g2vec/xc/vectors/{spec.rank}",
                               buf.getvalue())
    if spec.rank != 0:
        return path
    with open(path, "w") as fout:
        fout.write("GeneSymbol")
        for i in range(vectors_local.shape[1]):
            fout.write("\tV%d" % i)
        fout.write("\n")
        for r in range(spec.n_ranks):
            if r == 0:
                part = vectors_local
            else:
                part = np.load(_io.BytesIO(hostcomm.get_bytes_chunked(
                    f"g2vec/xc/vectors/{r}", deadline=sctx.deadline,
                    owner=r)), allow_pickle=False)
            rlo, rhi = spec.gene_range(r)
            if part.shape[0] != rhi - rlo:
                raise ValueError(
                    f"write_vectors_sharded: rank {r} published "
                    f"{part.shape[0]} rows for gene range [{rlo}, {rhi})")
            for gene, vector in zip(genes[rlo:rhi], part):
                fout.write(gene)
                for val in vector:
                    fout.write("\t%.6f" % val)
                fout.write("\n")
    return path


def write_stability(result_name: str, scenario: str,
                    meta: Sequence, columns: Sequence[str],
                    genes: Sequence[str],
                    rows: Sequence[Sequence[str]]) -> str:
    """The scenario reducer's artifact: ``<NAME>_stability.txt``.

    ``meta`` is an ordered sequence of ``(key, value)`` pairs rendered as
    ``# key\\tvalue`` lines; ``rows`` holds ONE PREFORMATTED string per
    cell (the reducer owns number formatting — "%.6f" floats, "na"
    sentinels), so this writer concatenates bytes and nothing else.
    """
    if len(genes) != len(rows):
        raise ValueError(f"write_stability: {len(genes)} genes vs "
                         f"{len(rows)} rows")
    path = result_name + "_stability.txt"
    with open(path, "w") as fout:
        fout.write("# g2vec stability v1\tscenario=%s\n" % scenario)
        for key, value in meta:
            fout.write("# %s\t%s\n" % (key, value))
        fout.write("GeneSymbol\t" + "\t".join(columns) + "\n")
        for gene, row in zip(genes, rows):
            if len(row) != len(columns):
                raise ValueError(
                    f"write_stability: row for {gene!r} has {len(row)} "
                    f"cells for {len(columns)} columns")
            fout.write(gene + "\t" + "\t".join(row) + "\n")
    return path


def write_inventory_bundle(bundle_dir: str, embeddings: np.ndarray,
                           genes: Sequence[str],
                           scores: Optional[np.ndarray],
                           meta: dict, ann_nlist: int = 0,
                           seed_centroids: Optional[np.ndarray] = None,
                           extra_files: Optional[dict] = None
                           ) -> str:
    """Publish one query-plane bundle generation under ``bundle_dir``.

    Generation-atomic: the new contents are staged in a ``.tmp.<pid>``
    sibling, renamed to ``<bundle_dir>/gen-NNNNNN``, and COMMITTED by
    rewriting the :data:`GENERATION` pointer (tmp + ``os.replace``,
    rename-last). A concurrent reader resolves the pointer once and
    maps entirely-old or entirely-new files — never a torn mix — and
    a crash anywhere before the pointer flip leaves the prior
    generation serving untouched (the orphan subdir is swept by the
    next publish). The previous generation is kept on disk so in-flight
    readers of the old pointer still resolve; older ones are removed.
    The sha256 manifest (written before the renames, atomically itself)
    is the read-side integrity gate: serve/inventory.py refuses to map
    a generation whose manifest is missing or whose hashes mismatch.

    ``extra_files`` maps extra file names to JSON-serializable objects
    written into the generation and sha256'd into its manifest — the
    update plane stores its ``delta_fingerprints.json`` this way
    (``delta_``/``ann_`` prefixed files ride the LENIENT verification
    tier: corruption costs incrementality or index coverage, never
    query correctness).

    ``scores`` may be ``None`` for a partial republication from the
    durable record's text outputs (the ``[2, G]`` score matrix is not
    recoverable from them); ``meta["has_scores"]`` records which kind
    this bundle is.

    ``ann_nlist`` gates the IVF index build (ops/ann.py:resolve_nlist —
    0 auto-indexes large bundles, <0 disables, >0 forces a list count);
    when an index is built its three files are sha256'd into the SAME
    manifest as the exact arrays and ``meta["ann"]`` records the build.
    ``seed_centroids`` (the stage-5 k-means centers, when the caller
    has them) seed the coarse quantizer for free; any shape mismatch
    silently falls back to the deterministic row seeding.
    """
    import time as _time

    from g2vec_tpu.utils.integrity import sha256_file, write_json_atomic

    embeddings = np.asarray(embeddings, dtype=np.float32)
    if embeddings.ndim != 2 or embeddings.shape[0] != len(genes):
        raise ValueError(
            f"write_inventory_bundle: embeddings {embeddings.shape} vs "
            f"{len(genes)} genes")
    from g2vec_tpu.ops import ann as ann_ops
    from g2vec_tpu.ops.knn import row_norms
    from g2vec_tpu.resilience.faults import fault_point

    bundle_dir = os.path.abspath(bundle_dir)
    tmp = f"{bundle_dir}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    np.save(os.path.join(tmp, "embeddings.npy"), embeddings,
            allow_pickle=False)
    np.save(os.path.join(tmp, "norms.npy"), row_norms(embeddings),
            allow_pickle=False)
    if scores is not None:
        scores = np.asarray(scores, dtype=np.float32)
        if scores.ndim != 2 or scores.shape[1] != embeddings.shape[0]:
            raise ValueError(
                f"write_inventory_bundle: scores {scores.shape} vs "
                f"[*, {embeddings.shape[0]}] expected")
        np.save(os.path.join(tmp, "scores.npy"), scores,
                allow_pickle=False)
    with open(os.path.join(tmp, "genes.txt"), "w") as fout:
        for gene in genes:
            fout.write("%s\n" % gene)
    nlist = ann_ops.resolve_nlist(embeddings.shape[0], ann_nlist)
    ann_meta = None
    if nlist:
        t0 = _time.perf_counter()
        centroids, postings, offsets = ann_ops.build_ivf(
            embeddings, nlist, seed_centroids=seed_centroids)
        np.save(os.path.join(tmp, "ann_centroids.npy"), centroids,
                allow_pickle=False)
        np.save(os.path.join(tmp, "ann_postings.npy"), postings,
                allow_pickle=False)
        np.save(os.path.join(tmp, "ann_offsets.npy"), offsets,
                allow_pickle=False)
        # Posting-major vector copy: the RAW float32 rows reordered so
        # a probed list's candidates are one contiguous slab (streams)
        # instead of a fancy-indexed gather (~100 ns/row of cache
        # misses). Raw — not pre-normalized — because bitwise equality
        # with the gather path requires the identical row-dot-then-
        # divide arithmetic of ops/knn.
        np.save(os.path.join(tmp, "ann_vectors.npy"),
                np.ascontiguousarray(embeddings[postings]),
                allow_pickle=False)
        score_topm = 0
        if scores is not None:
            from g2vec_tpu.ops.knn import topk_scores
            score_topm = min(int(embeddings.shape[0]), ANN_SCORE_TOPM)
            short = np.stack([topk_scores(scores[r], score_topm)[0]
                              for r in range(scores.shape[0])])
            np.save(os.path.join(tmp, "ann_scores.npy"),
                    short.astype(np.int64), allow_pickle=False)
        ann_meta = {"format": ann_ops.ANN_FORMAT, "nlist": int(nlist),
                    "nprobe_default": ann_ops.DEFAULT_NPROBE,
                    "posting_major": True,
                    "score_topm": int(score_topm),
                    "seeded": bool(
                        seed_centroids is not None
                        and np.asarray(seed_centroids).ndim == 2
                        and np.asarray(seed_centroids).shape[1]
                        == embeddings.shape[1]),
                    "build_ms": round(
                        (_time.perf_counter() - t0) * 1000.0, 3)}
    for name, obj in sorted((extra_files or {}).items()):
        write_json_atomic(os.path.join(tmp, name), obj)
    meta = dict(meta, n_genes=int(embeddings.shape[0]),
                hidden=int(embeddings.shape[1]),
                has_scores=scores is not None, ann=ann_meta)
    write_json_atomic(os.path.join(tmp, "meta.json"), meta)
    files = {}
    for name in sorted(os.listdir(tmp)):
        files[name] = {"sha256": sha256_file(os.path.join(tmp, name)),
                       "bytes": os.path.getsize(os.path.join(tmp, name))}
    write_json_atomic(os.path.join(tmp, INVENTORY_MANIFEST),
                      {"format": "g2vec-inventory-v1", "files": files})
    if nlist:
        # AFTER the manifest, BEFORE the rename: a kind=corrupt here
        # publishes a bundle whose index bytes no longer match their
        # manifest hash — the torn-index drill the lenient map path
        # (serve/inventory.py) must catch and downgrade to exact.
        fault_point("ann_build",
                    path=os.path.join(tmp, "ann_postings.npy"))
    os.makedirs(bundle_dir, exist_ok=True)
    gen_name = "gen-%06d" % _next_generation(bundle_dir)
    os.rename(tmp, os.path.join(bundle_dir, gen_name))
    # BEFORE the pointer flip: a kind=crash here leaves the new
    # generation orphaned and the OLD pointer serving — the mid-flip
    # SIGKILL drill; journal recovery replays the publish. A
    # kind=corrupt flips bytes in the pointer the reader must refuse.
    fault_point("update_publish",
                path=os.path.join(bundle_dir, GENERATION_FILE))
    ptmp = os.path.join(bundle_dir, f".{GENERATION_FILE}.tmp.{os.getpid()}")
    with open(ptmp, "w") as f:
        f.write(gen_name + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(ptmp, os.path.join(bundle_dir, GENERATION_FILE))
    _gc_generations(bundle_dir, gen_name)
    return os.path.join(bundle_dir, gen_name)


def _gc_generations(bundle_dir: str, live: str) -> None:
    """Sweep everything but the live generation, its immediate
    predecessor (in-flight readers of the just-replaced pointer must
    still resolve; the no-delta byte-identity check also compares
    across the last flip), and the pointer itself. Removes legacy flat
    bundle files on the first generational publish over an old-layout
    bundle — open maps of them stay valid (POSIX unlink semantics)."""
    try:
        serial = int(live[4:])
    except ValueError:
        return
    keep = {live, "gen-%06d" % (serial - 1), GENERATION_FILE}
    for name in sorted(os.listdir(bundle_dir)):
        if name in keep:
            continue
        path = os.path.join(bundle_dir, name)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
        except OSError:
            pass


def write_vectors(result_name: str, vectors: np.ndarray,
                  genes: Sequence[str]) -> str:
    path = result_name + "_vectors.txt"
    vectors = np.asarray(vectors, dtype=np.float32)
    if len(genes) != vectors.shape[0]:
        raise ValueError(f"write_vectors: {len(genes)} genes vs "
                         f"{vectors.shape[0]} embedding rows")
    with open(path, "w") as fout:
        fout.write("GeneSymbol")
        for i in range(vectors.shape[1]):
            fout.write("\tV%d" % i)
        fout.write("\n")
        for gene, vector in zip(genes, vectors):
            fout.write(gene)
            for val in vector:
                fout.write("\t%.6f" % val)
            fout.write("\n")
    return path
