"""L1 — TSV readers.

File contracts (ref: G2Vec.py:436-503): whole-file read, ``rstrip()`` per line
(so trailing whitespace / CRLF files work), split on tabs, header row skipped.

- Expression (ref: G2Vec.py:478-503): header = ``PATIENT\\t<sample ids...>``;
  each body row = ``gene\\tfloat...``; the matrix is stored gene-major in the
  file and transposed to samples x genes in memory (ref: G2Vec.py:498).
- Clinical (ref: G2Vec.py:436-453): header + ``sample\\tint_label`` rows;
  label 0 = good prognosis, 1 = poor prognosis.
- Network (ref: G2Vec.py:455-476): header ``src\\tdest`` + one directed edge
  per row; edges keep file order and direction; the gene set is the set of all
  endpoints.

Unlike the reference, readers validate shapes and raise actionable errors
instead of crashing with raw IndexErrors. A fast C++ parser is used for the
expression matrix when available (see g2vec_tpu/native), falling back to the
pure-Python path transparently.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

#: When set (the edge-partition fleet tests set it in every rank's env),
#: :func:`load_network` refuses to run — the acceptance pin that no code
#: path materializes the full edge list under ``--edge-partition``.
FORBID_FULL_NETWORK_ENV = "G2VEC_FORBID_FULL_NETWORK"


@dataclasses.dataclass
class ExpressionData:
    """samples x genes float32 matrix plus axis labels.

    ``expr[i, j]`` is the expression of ``gene[j]`` in ``sample[i]`` —
    the same layout the reference builds at G2Vec.py:498-502.
    ``label`` is attached later by preprocess.match_labels.
    """

    sample: np.ndarray  # [n_samples] str
    gene: np.ndarray    # [n_genes] str
    expr: np.ndarray    # [n_samples, n_genes] float32
    label: np.ndarray | None = None  # [n_samples] int32, set by match_labels


@dataclasses.dataclass
class NetworkData:
    """Directed edge list (file order preserved) + endpoint gene set."""

    edges: List[Tuple[str, str]]
    genes: set


_warned_native = False


def _read_tsv_lines(path: str) -> List[List[str]]:
    with open(path) as fin:
        lines = fin.readlines()
    rows = [line.rstrip().split("\t") for line in lines]
    # Tolerate trailing blank lines (rstrip -> [''])
    return [r for r in rows if r != [""]]


def load_expression(path: str, use_native: bool = True) -> ExpressionData:
    """Read a gene-expression TSV (ref: G2Vec.py:478-503 contract)."""
    if use_native:
        # Unavailability (no toolchain, load failure) falls back to the
        # Python parser with a one-time warning; actual PARSE errors
        # (ValueError) propagate — a malformed file is malformed in any
        # language and must not be silently re-parsed.
        try:
            from g2vec_tpu.native import bindings as _native

            parsed = _native.read_expression(path)
        except (RuntimeError, ImportError, OSError) as e:
            global _warned_native
            if not _warned_native:
                _warned_native = True
                import warnings

                warnings.warn(f"native TSV reader unavailable ({e!r}); "
                              "using the Python parser", RuntimeWarning)
        else:
            sample, gene, expr = parsed
            return ExpressionData(sample=sample, gene=gene, expr=expr)
    rows = _read_tsv_lines(path)
    if len(rows) < 2:
        raise ValueError(f"{path}: expression file needs a header and at least one gene row")
    sample = np.array(rows[0][1:])
    n_samples = len(sample)
    genes: List[str] = []
    values: List[List[str]] = []
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) - 1 != n_samples:
            raise ValueError(
                f"{path}:{ln}: gene {row[0]!r} has {len(row) - 1} values, "
                f"expected {n_samples} (one per sample in the header)")
        genes.append(row[0])
        values.append(row[1:])
    gene = np.array(genes)
    try:
        expr = np.array(values, dtype=np.float32).T  # gene-major file -> samples x genes
    except ValueError as e:
        raise ValueError(f"{path}: non-numeric expression value ({e})") from e
    return ExpressionData(sample=sample, gene=gene, expr=expr)


def load_clinical(path: str) -> Dict[str, int]:
    """Read clinical labels (ref: G2Vec.py:436-453 contract)."""
    rows = _read_tsv_lines(path)
    if len(rows) < 2:
        raise ValueError(f"{path}: clinical file needs a header and at least one row")
    result: Dict[str, int] = {}
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) < 2:
            raise ValueError(f"{path}:{ln}: expected 'sample\\tlabel', got {row!r}")
        try:
            label = int(row[1])
        except ValueError as e:
            raise ValueError(f"{path}:{ln}: label must be an integer, got {row[1]!r}") from e
        if label not in (0, 1):
            raise ValueError(f"{path}:{ln}: label must be 0 (good) or 1 (poor), got {label}")
        if row[0] in result and result[row[0]] != label:
            raise ValueError(
                f"{path}:{ln}: sample {row[0]!r} appears twice with conflicting labels")
        result[row[0]] = label
    return result


def load_network(path: str) -> NetworkData:
    """Read a directed gene-interaction edge list (ref: G2Vec.py:455-476 contract)."""
    if os.environ.get(FORBID_FULL_NETWORK_ENV):
        raise RuntimeError(
            f"load_network({path!r}) reached with {FORBID_FULL_NETWORK_ENV} "
            "set — an --edge-partition run tried to materialize the full "
            "edge list; use scan_network_genes + load_network_range")
    rows = _read_tsv_lines(path)
    if len(rows) < 1:
        raise ValueError(f"{path}: network file needs a header row")
    edges: List[Tuple[str, str]] = []
    genes: set = set()
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) < 2:
            raise ValueError(f"{path}:{ln}: expected 'src\\tdest', got {row!r}")
        edges.append((row[0], row[1]))
        genes.add(row[0])
        genes.add(row[1])
    return NetworkData(edges=edges, genes=genes)


# ---------------------------------------------------------------------------
# Edge-partitioned loading (--edge-partition): the full edge list never
# materializes on any rank. Gene NAMES are still scanned globally (the
# sorted-common-intersection invariant needs the endpoint set — O(G)
# strings, not O(E) edges); edges are then streamed a second time with a
# src-index range filter, so a rank holds only its owned rows' edges.
# Both the plain one-file network TSV and the pre-partitioned shard
# layout written by ``tools/make_synth_graph.py --partitions R`` (part
# files + sha256 manifest) feed the same two entry points.
# ---------------------------------------------------------------------------


def _iter_network_rows(path: str):
    """Stream (lineno, src, dst) from a network TSV without holding the
    file; same tolerance (rstrip, blank lines) as :func:`load_network`."""
    with open(path) as fin:
        header = fin.readline()
        if not header:
            raise ValueError(f"{path}: network file needs a header row")
        for ln, line in enumerate(fin, start=2):
            row = line.rstrip().split("\t")
            if row == [""]:
                continue
            if len(row) < 2:
                raise ValueError(
                    f"{path}:{ln}: expected 'src\\tdest', got {row!r}")
            yield ln, row[0], row[1]


def scan_network_genes(path: str) -> set:
    """Streamed endpoint gene set of a network TSV (or every part file
    of a partition manifest) — the edge-partition substitute for
    ``load_network(...).genes``; edges are discarded as read."""
    if path.endswith(".json"):
        manifest = read_partition_manifest(path)
        base = os.path.dirname(os.path.abspath(path))
        genes_path = os.path.join(base, manifest["genes_file"])
        with open(genes_path) as f:
            return {line.rstrip("\n") for line in f if line.rstrip("\n")}
    genes: set = set()
    for _, a, b in _iter_network_rows(path):
        genes.add(a)
        genes.add(b)
    return genes


def load_network_range(path: str, gene2idx: Dict[str, int], lo: int,
                       hi: int) -> Tuple[np.ndarray, np.ndarray]:
    """Streamed ``restrict_network`` + ``edges_to_indices`` + src-range
    filter in one pass: (src_idx, dst_idx) int32 arrays of the directed
    edges whose endpoints are both common (in ``gene2idx``) and whose
    src index falls in [lo, hi), file order preserved.

    Order contract: dropping out-of-range-src edges commutes with both
    the |PCC| threshold's first-occurrence dedup (the dedup key contains
    src) and edges_to_csr's stable src sort (within-row order is file
    order among SAME-src edges, all of which share this range) — so the
    partitioned CSR's owned rows are byte-identical to the unpartitioned
    CSR's same rows.
    """
    if path.endswith(".json"):
        return _load_partitioned_range(path, gene2idx, lo, hi)
    src: List[int] = []
    dst: List[int] = []
    for _, a, b in _iter_network_rows(path):
        si = gene2idx.get(a)
        if si is None or not (lo <= si < hi):
            continue
        di = gene2idx.get(b)
        if di is None:
            continue
        src.append(si)
        dst.append(di)
    return (np.array(src, dtype=np.int32), np.array(dst, dtype=np.int32))


def read_partition_manifest(path: str) -> dict:
    """Load + schema-check a ``--partitions`` manifest (written by
    tools/make_synth_graph.py via data/synth.py)."""
    with open(path) as f:
        manifest = json.load(f)
    for key in ("format", "partitions", "genes_file", "files"):
        if key not in manifest:
            raise ValueError(f"{path}: partition manifest missing {key!r}")
    if manifest["format"] != "g2vec-network-partitions-v1":
        raise ValueError(
            f"{path}: unknown partition manifest format "
            f"{manifest['format']!r}")
    if len(manifest["files"]) != manifest["partitions"]:
        raise ValueError(
            f"{path}: manifest lists {len(manifest['files'])} files for "
            f"{manifest['partitions']} partitions")
    for entry in manifest["files"]:
        for key in ("name", "sha256", "n_edges", "gene_lo", "gene_hi"):
            if key not in entry:
                raise ValueError(
                    f"{path}: manifest file entry missing {key!r}")
    return manifest


def _load_partitioned_range(manifest_path: str, gene2idx: Dict[str, int],
                            lo: int, hi: int
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Range read over pre-partitioned shard files: only part files
    whose NAME range can intersect the requested index range are opened
    (gene indices are positions in the SORTED common list, so an index
    range is a contiguous name range), and each opened file's sha256 is
    verified against the manifest first.
    """
    from g2vec_tpu.utils.integrity import sha256_file

    manifest = read_partition_manifest(manifest_path)
    base = os.path.dirname(os.path.abspath(manifest_path))
    if hi <= lo:
        return (np.zeros(0, dtype=np.int32), np.zeros(0, dtype=np.int32))
    # Names of the requested index range, in sorted-common order.
    by_idx = sorted(gene2idx, key=gene2idx.get)
    name_lo, name_hi = by_idx[lo], by_idx[hi - 1]
    src_parts: List[np.ndarray] = []
    dst_parts: List[np.ndarray] = []
    for entry in manifest["files"]:
        # Part holds src names in [gene_lo, gene_hi]; skip when the
        # whole part sorts outside the requested name range.
        if entry["gene_hi"] < name_lo or entry["gene_lo"] > name_hi:
            continue
        part = os.path.join(base, entry["name"])
        digest = sha256_file(part)
        if digest != entry["sha256"]:
            raise ValueError(
                f"{part}: sha256 mismatch vs manifest ({digest} != "
                f"{entry['sha256']}) — partition file corrupt or stale")
        s, d = load_network_range(part, gene2idx, lo, hi)
        src_parts.append(s)
        dst_parts.append(d)
    if not src_parts:
        return (np.zeros(0, dtype=np.int32), np.zeros(0, dtype=np.int32))
    return np.concatenate(src_parts), np.concatenate(dst_parts)
