"""L1 — TSV readers.

File contracts (ref: G2Vec.py:436-503): whole-file read, ``rstrip()`` per line
(so trailing whitespace / CRLF files work), split on tabs, header row skipped.

- Expression (ref: G2Vec.py:478-503): header = ``PATIENT\\t<sample ids...>``;
  each body row = ``gene\\tfloat...``; the matrix is stored gene-major in the
  file and transposed to samples x genes in memory (ref: G2Vec.py:498).
- Clinical (ref: G2Vec.py:436-453): header + ``sample\\tint_label`` rows;
  label 0 = good prognosis, 1 = poor prognosis.
- Network (ref: G2Vec.py:455-476): header ``src\\tdest`` + one directed edge
  per row; edges keep file order and direction; the gene set is the set of all
  endpoints.

Unlike the reference, readers validate shapes and raise actionable errors
instead of crashing with raw IndexErrors. A fast C++ parser is used for the
expression matrix when available (see g2vec_tpu/native), falling back to the
pure-Python path transparently.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np


@dataclasses.dataclass
class ExpressionData:
    """samples x genes float32 matrix plus axis labels.

    ``expr[i, j]`` is the expression of ``gene[j]`` in ``sample[i]`` —
    the same layout the reference builds at G2Vec.py:498-502.
    ``label`` is attached later by preprocess.match_labels.
    """

    sample: np.ndarray  # [n_samples] str
    gene: np.ndarray    # [n_genes] str
    expr: np.ndarray    # [n_samples, n_genes] float32
    label: np.ndarray | None = None  # [n_samples] int32, set by match_labels


@dataclasses.dataclass
class NetworkData:
    """Directed edge list (file order preserved) + endpoint gene set."""

    edges: List[Tuple[str, str]]
    genes: set


_warned_native = False


def _read_tsv_lines(path: str) -> List[List[str]]:
    with open(path) as fin:
        lines = fin.readlines()
    rows = [line.rstrip().split("\t") for line in lines]
    # Tolerate trailing blank lines (rstrip -> [''])
    return [r for r in rows if r != [""]]


def load_expression(path: str, use_native: bool = True) -> ExpressionData:
    """Read a gene-expression TSV (ref: G2Vec.py:478-503 contract)."""
    if use_native:
        # Unavailability (no toolchain, load failure) falls back to the
        # Python parser with a one-time warning; actual PARSE errors
        # (ValueError) propagate — a malformed file is malformed in any
        # language and must not be silently re-parsed.
        try:
            from g2vec_tpu.native import bindings as _native

            parsed = _native.read_expression(path)
        except (RuntimeError, ImportError, OSError) as e:
            global _warned_native
            if not _warned_native:
                _warned_native = True
                import warnings

                warnings.warn(f"native TSV reader unavailable ({e!r}); "
                              "using the Python parser", RuntimeWarning)
        else:
            sample, gene, expr = parsed
            return ExpressionData(sample=sample, gene=gene, expr=expr)
    rows = _read_tsv_lines(path)
    if len(rows) < 2:
        raise ValueError(f"{path}: expression file needs a header and at least one gene row")
    sample = np.array(rows[0][1:])
    n_samples = len(sample)
    genes: List[str] = []
    values: List[List[str]] = []
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) - 1 != n_samples:
            raise ValueError(
                f"{path}:{ln}: gene {row[0]!r} has {len(row) - 1} values, "
                f"expected {n_samples} (one per sample in the header)")
        genes.append(row[0])
        values.append(row[1:])
    gene = np.array(genes)
    try:
        expr = np.array(values, dtype=np.float32).T  # gene-major file -> samples x genes
    except ValueError as e:
        raise ValueError(f"{path}: non-numeric expression value ({e})") from e
    return ExpressionData(sample=sample, gene=gene, expr=expr)


def load_clinical(path: str) -> Dict[str, int]:
    """Read clinical labels (ref: G2Vec.py:436-453 contract)."""
    rows = _read_tsv_lines(path)
    if len(rows) < 2:
        raise ValueError(f"{path}: clinical file needs a header and at least one row")
    result: Dict[str, int] = {}
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) < 2:
            raise ValueError(f"{path}:{ln}: expected 'sample\\tlabel', got {row!r}")
        try:
            label = int(row[1])
        except ValueError as e:
            raise ValueError(f"{path}:{ln}: label must be an integer, got {row[1]!r}") from e
        if label not in (0, 1):
            raise ValueError(f"{path}:{ln}: label must be 0 (good) or 1 (poor), got {label}")
        if row[0] in result and result[row[0]] != label:
            raise ValueError(
                f"{path}:{ln}: sample {row[0]!r} appears twice with conflicting labels")
        result[row[0]] = label
    return result


def load_network(path: str) -> NetworkData:
    """Read a directed gene-interaction edge list (ref: G2Vec.py:455-476 contract)."""
    rows = _read_tsv_lines(path)
    if len(rows) < 1:
        raise ValueError(f"{path}: network file needs a header row")
    edges: List[Tuple[str, str]] = []
    genes: set = set()
    for ln, row in enumerate(rows[1:], start=2):
        if len(row) < 2:
            raise ValueError(f"{path}:{ln}: expected 'src\\tdest', got {row!r}")
        edges.append((row[0], row[1]))
        genes.add(row[0])
        genes.add(row[1])
    return NetworkData(edges=edges, genes=genes)
