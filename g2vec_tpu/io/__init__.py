"""L1/L6 — data IO: TSV readers and byte-identical output writers."""
from g2vec_tpu.io.readers import (  # noqa: F401
    ExpressionData,
    NetworkData,
    load_clinical,
    load_expression,
    load_network,
)
from g2vec_tpu.io.writers import (  # noqa: F401
    write_biomarkers,
    write_lgroups,
    write_vectors,
)
