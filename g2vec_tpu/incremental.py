"""Incremental update plane — delta re-walk + warm-start fine-tune.

A cold run re-walks the whole graph and trains from a seeded draw even
when the inputs barely moved (ten new patients, one new edge). This
module is the ``update`` serve op's engine: it diffs the NEW inputs
against the prior bundle generation's recorded fingerprints at
owner-range granularity, re-walks only the changed ranges plus their
1-hop frontier through the native sampler pool, warm-starts training
from the prior embedding, and hands the daemon everything it needs for
a generation-atomic republish (io/writers.py owns the pointer flip).

Delta model (the contract, pinned by tests/test_update.py):

- The gene axis splits into at most :data:`RANGE_CAP` contiguous owner
  ranges. Each (group, range) is fingerprinted over the range's
  OUTGOING thresholded-CSR edges + the walk parameters; the full
  thresholded CSR keeps its existing whole-graph walk-cache key too,
  so an untouched group hits the sha256 walk cache byte-for-byte.
- A changed range is re-walked; so is its 1-HOP FRONTIER (every range
  holding a neighbor of a changed range's genes) — an edge insertion
  perturbs the walk distribution of both endpoints' neighborhoods.
  Unchanged ranges load their per-range artifacts from the walk cache
  under :data:`RANGE_FAMILY` keys.
- This is deliberately an APPROXIMATION: a re-walked range's walks
  wander the updated graph, a cached range's walks wandered the old
  one. Correctness is therefore pinned STATISTICALLY — the PR 7 band
  (|dACC| <= :data:`BAND_DACC`, top-N biomarker overlap >=
  :data:`BAND_OVERLAP`) against a cold retrain on the same updated
  inputs — never bitwise.
- Expression/label-only changes (the thresholded CSR survives the new
  expression bytes) skip stage 3 entirely; a fully unchanged input set
  short-circuits to a no-op republish whose array files are
  byte-identical to the prior generation (walked == 0).

Warm start preserves the PR 4 init contract: the full seeded draw is
taken at the NEW gene count (so a new gene's row comes from the same
global truncated-normal draw a cold run would give it, independent of
layout padding), then carried-over genes' rows are overwritten with
the prior bundle's embedding, matched by symbol.

Fingerprints travel inside the bundle as ``delta_fingerprints.json``
on the lenient (``delta_``-prefixed) manifest tier: corruption costs
a full re-walk on the next update, never a wrong query answer. A cold
bundle has no fingerprints; the first update over one "bootstraps" —
whole-graph cache hits still apply, per-range artifacts and
fingerprints are recorded, and the NEXT no-delta update re-walks
nothing.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

#: Owner-range count cap for delta detection. Small enough that the
#: per-range fingerprint/artifact overhead is negligible, large enough
#: that a 1% edge delta dirties only a few percent of ranges.
RANGE_CAP = 32
#: PRNG/artifact family tag for per-range walk-cache entries — a
#: distinct namespace from the whole-graph NATIVE_FAMILY artifacts.
RANGE_FAMILY = "incremental-range-v1"
#: delta_fingerprints.json wire format tag.
DELTA_FORMAT = "g2vec-delta-v1"
#: The PR 7 statistical band, the update plane's correctness contract
#: vs a cold retrain on the same updated inputs.
BAND_DACC = 0.20
BAND_OVERLAP = 0.6
#: Row bucket for the warm-start fine-tune's padded path count.
#: Successive updates dedup to path counts that drift by a handful of
#: rows; without bucketing every fine-tune lands on a fresh program
#: shape and the per-update wall is dominated by XLA recompiles. The
#: padding is inert (weight-0 masked rows, see train_cbow).
FINE_TUNE_ROW_BUCKET = 512


def resolve_ranges(n_genes: int, cap: int = RANGE_CAP
                   ) -> List[Tuple[int, int]]:
    """Deterministic contiguous owner ranges over the gene axis."""
    n_genes = int(n_genes)
    if n_genes <= 0:
        return []
    n = min(int(cap), n_genes)
    step = -(-n_genes // n)
    return [(lo, min(lo + step, n_genes))
            for lo in range(0, n_genes, step)]


def _params_tag(cfg) -> str:
    """Everything (besides the CSR bytes) a group's walks depend on."""
    return (f"len_path={cfg.lenPath};reps={cfg.numRepetition};"
            f"seed={cfg.seed};threshold={cfg.pcc_threshold};"
            f"backend=native")


def range_fingerprint(s: np.ndarray, d: np.ndarray, w: np.ndarray,
                      lo: int, hi: int, params_tag: str) -> str:
    """sha256 of one owner range's outgoing thresholded edges + the
    walk params. Edges are hashed in their (deterministic) builder
    order; the mask keeps relative order so equal inputs hash equal."""
    mask = (s >= lo) & (s < hi)
    h = hashlib.sha256()
    h.update(f"fmt={DELTA_FORMAT};range={lo}:{hi};"
             f"{params_tag};".encode())
    for arr, dtype in ((s[mask], np.int32), (d[mask], np.int32),
                       (w[mask], np.float32)):
        a = np.ascontiguousarray(np.asarray(arr), dtype=dtype)
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _range_walk_key(fp: str, lo: int, hi: int, n_genes: int,
                    params_tag: str) -> str:
    """Walk-cache key for one (group, range) artifact. Keyed by the
    RANGE fingerprint, not the whole graph — reusing an unchanged
    range's walks across a distant-graph change is the documented
    approximation the statistical band covers."""
    h = hashlib.sha256()
    h.update(f"family={RANGE_FAMILY};range={lo}:{hi};"
             f"n_genes={n_genes};{params_tag};fp={fp}".encode())
    return h.hexdigest()


def _sha(*chunks: bytes) -> str:
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.hexdigest()


def compute_fingerprints(cfg, genes: Sequence[str], expr: np.ndarray,
                         labels: np.ndarray,
                         group_csrs: Sequence[Tuple[np.ndarray,
                                                    np.ndarray,
                                                    np.ndarray]],
                         group_ckeys: Sequence[str]) -> dict:
    """The ``delta_fingerprints.json`` payload for one publication."""
    n_genes = len(genes)
    tag = _params_tag(cfg)
    ranges = resolve_ranges(n_genes)
    groups = []
    for (s, d, w), ckey in zip(group_csrs, group_ckeys):
        s = np.asarray(s)
        groups.append({
            "ckey": ckey,
            "ranges": [range_fingerprint(s, np.asarray(d),
                                         np.asarray(w), lo, hi, tag)
                       for lo, hi in ranges]})
    return {
        "format": DELTA_FORMAT,
        "n_genes": n_genes,
        "n_ranges": len(ranges),
        "params": tag,
        "genes_sha256": _sha("\n".join(genes).encode()),
        "expr_sha256": _sha(
            np.ascontiguousarray(expr, dtype=np.float32).tobytes(),
            np.ascontiguousarray(labels, dtype=np.int32).tobytes()),
        "groups": groups,
    }


def frontier_ranges(changed: Set[int], ranges: List[Tuple[int, int]],
                    s: np.ndarray, d: np.ndarray) -> Set[int]:
    """Ranges holding any 1-hop neighbor of a changed range's genes
    (both edge directions, so asymmetric edge lists still dirty both
    endpoints' owners)."""
    if not changed or not ranges:
        return set()
    bounds = np.asarray([r[0] for r in ranges] + [ranges[-1][1]])
    in_changed = np.zeros(int(bounds[-1]), dtype=bool)
    for ri in changed:
        lo, hi = ranges[ri]
        in_changed[lo:hi] = True
    s = np.asarray(s)
    d = np.asarray(d)
    neigh = np.concatenate([d[in_changed[s]], s[in_changed[d]]]) \
        if s.size else np.empty(0, dtype=np.int64)
    if neigh.size == 0:
        return set()
    owners = np.searchsorted(bounds, np.unique(neigh), side="right") - 1
    return {int(o) for o in owners if 0 <= o < len(ranges)}


@dataclasses.dataclass
class UpdateResult:
    """Everything the daemon needs to republish + report one update."""
    genes: List[str]
    embeddings: np.ndarray              # float32 [G, H]
    biomarker_scores: Optional[np.ndarray]   # float32 [2, G]
    biomarkers: List[str]
    km_centers: Optional[np.ndarray]    # stage-5 centers (ANN seed)
    fingerprints: dict                  # delta_fingerprints.json payload
    acc_val: float
    stats: dict                         # mode/walked/ranges/cache_hits


def _load_inputs(cfg):
    """Pipeline stages 1-2 (the solo, non-streamed path): load,
    label-match, sorted-intersection restrict, edge index."""
    from g2vec_tpu.io.readers import (load_clinical, load_expression,
                                      load_network)
    from g2vec_tpu.preprocess import (edges_to_indices, find_common_genes,
                                      make_gene2idx, match_labels,
                                      restrict_data, restrict_network)

    data = load_expression(cfg.expression_file,
                           use_native=cfg.use_native_io)
    clinical = load_clinical(cfg.clinical_file)
    network = load_network(cfg.network_file)
    data.label = match_labels(clinical, data.sample)
    common = find_common_genes(network.genes, data.gene)
    network = restrict_network(network, common)
    data = restrict_data(data, common)
    gene2idx = make_gene2idx(data.gene)
    src, dst = edges_to_indices(network, gene2idx)
    return data, np.asarray(src), np.asarray(dst)


def _group_walks(cfg, i: int, s: np.ndarray, d: np.ndarray,
                 w: np.ndarray, n_genes: int, ckey: str,
                 prior_group: Optional[dict], new_ranges_fp: List[str],
                 walk_cache, emit: Callable, group: str,
                 force_all: bool) -> Tuple[Set[bytes], dict]:
    """One group's path set under the delta plan. Returns (path_set,
    per-group stats). Walks are produced PER RANGE via the native
    sampler's walker-axis slicing, so the union over all ranges is
    bit-identical to the whole-graph call for the same seed."""
    from g2vec_tpu.ops.host_walker import edges_to_csr, walk_packed_rows

    tag = _params_tag(cfg)
    ranges = resolve_ranges(n_genes)
    stats = {"ranges_total": len(ranges), "ranges_rewalked": 0,
             "walked_rows": 0, "cache_hits": 0, "outcome": "delta"}

    # Whole-graph short-circuit: fingerprint-equal CSR -> the existing
    # sha256 walk cache, byte-for-byte (a cold run with the same cache
    # dir stored this artifact already).
    prior_ranges = (prior_group or {}).get("ranges")
    group_unchanged = (not force_all and prior_group is not None
                      and prior_group.get("ckey") == ckey
                      and prior_ranges == new_ranges_fp)
    if group_unchanged and walk_cache is not None:
        cached = walk_cache.load(ckey)
        if cached is not None:
            stats["outcome"] = "cache"
            stats["cache_hits"] = len(ranges)
            emit("delta_walk", group=group, **stats)
            return cached, stats

    if force_all or prior_group is None \
            or prior_group.get("ranges") is None \
            or len(prior_ranges or []) != len(ranges):
        rewalk = set(range(len(ranges)))
        stats["outcome"] = "bootstrap" if not force_all else "full"
    else:
        changed = {ri for ri, fp in enumerate(new_ranges_fp)
                   if fp != prior_ranges[ri]}
        rewalk = changed | frontier_ranges(changed, ranges, s, d)

    csr = edges_to_csr(s, d, w, n_genes)
    seed = (cfg.seed << 1) | i
    reps = cfg.numRepetition
    ps: Set[bytes] = set()
    for ri, (lo, hi) in enumerate(ranges):
        rkey = _range_walk_key(new_ranges_fp[ri], lo, hi, n_genes, tag)
        if ri not in rewalk and walk_cache is not None:
            cached = walk_cache.load(rkey)
            if cached is not None:
                ps |= cached
                stats["cache_hits"] += 1
                continue
            # Missing per-range artifact (cold prior, evicted cache):
            # walk it — counted, so "walked == 0" claims stay honest.
        parts = [walk_packed_rows(
            s, d, w, n_genes, len_path=cfg.lenPath, reps=reps,
            seed=seed, n_threads=cfg.sampler_threads, csr=csr,
            walker_lo=rep * n_genes + lo, walker_hi=rep * n_genes + hi)
            for rep in range(reps)]
        rows = np.vstack(parts) if parts else \
            np.zeros((0, (n_genes + 7) // 8), dtype=np.uint8)
        rset = {row.tobytes() for row in rows}
        stats["ranges_rewalked"] += 1
        stats["walked_rows"] += int(rows.shape[0])
        if walk_cache is not None:
            walk_cache.store(rkey, rset, n_genes,
                             meta={"group": group, "range": [lo, hi]})
        ps |= rset
    if walk_cache is not None and stats["ranges_rewalked"]:
        # Keep the whole-graph artifact current too, so the next
        # unchanged-group update (and any cold run of these exact
        # inputs) hits in one read.
        walk_cache.store(ckey, ps, n_genes, meta={"group": group})
    emit("delta_walk", group=group, **stats)
    return ps, stats


def run_update(cfg, prior_dir: str, *, walk_cache=None,
               epochs: int = 0, console: Callable = lambda *_: None,
               check: Optional[Callable] = None,
               emit: Optional[Callable] = None) -> UpdateResult:
    """Delta-detect, re-walk, warm-start fine-tune, rescore.

    ``cfg`` is a full G2VecConfig for the UPDATED inputs (the same
    validated job config a cold ``submit`` of them would run);
    ``prior_dir`` is the prior bundle's ROOT (its live generation is
    resolved through the pointer). ``epochs`` bounds the fine-tune
    (0 -> ``max(3, cfg.epoch // 4)``); the existing early-stop still
    applies within the bound. Publication is the CALLER's job —
    the daemon feeds the returned arrays + fingerprints to
    ``write_inventory_bundle`` so solo and served updates publish
    byte-identical twins.
    """
    emit = emit or (lambda *_a, **_k: None)
    t0 = time.perf_counter()
    from g2vec_tpu.cache import NATIVE_FAMILY, walk_cache_key
    from g2vec_tpu.ops.graph import thresholded_edges
    from g2vec_tpu.serve.inventory import _Bundle

    prior = _Bundle(os.path.abspath(prior_dir))
    data, src, dst = _load_inputs(cfg)
    n_genes = len(data.gene)
    if n_genes == 0:
        raise ValueError("update: no common genes between the updated "
                         "network and expression inputs")
    tag = _params_tag(cfg)
    ranges = resolve_ranges(n_genes)

    # ---- fingerprint the updated inputs --------------------------------
    group_csrs, group_ckeys, group_fps = [], [], []
    for i in range(2):
        expr_group = data.expr[data.label == i]
        s_k, d_k, w_k = thresholded_edges(expr_group, src, dst,
                                          threshold=cfg.pcc_threshold)
        s_k, d_k, w_k = (np.asarray(s_k), np.asarray(d_k),
                         np.asarray(w_k))
        group_csrs.append((s_k, d_k, w_k))
        group_ckeys.append(walk_cache_key(
            s_k, d_k, w_k, n_genes, len_path=cfg.lenPath,
            reps=cfg.numRepetition, seed=(cfg.seed << 1) | i,
            family=NATIVE_FAMILY))
        group_fps.append([range_fingerprint(s_k, d_k, w_k, lo, hi, tag)
                          for lo, hi in ranges])
    new_fp = compute_fingerprints(cfg, data.gene, data.expr, data.label,
                                  group_csrs, group_ckeys)
    new_fp["groups"] = [
        {"ckey": ck, "ranges": fps}
        for ck, fps in zip(group_ckeys, group_fps)]

    prior_fp = prior.fingerprints
    fp_ok = bool(prior_fp and prior_fp.get("format") == DELTA_FORMAT
                 and prior_fp.get("params") == tag)
    same_genes = list(prior.genes) == list(data.gene)

    # ---- no-delta short-circuit ----------------------------------------
    if fp_ok and same_genes \
            and prior_fp.get("genes_sha256") == new_fp["genes_sha256"] \
            and prior_fp.get("expr_sha256") == new_fp["expr_sha256"] \
            and [g.get("ckey") for g in prior_fp.get("groups", [])] \
            == group_ckeys:
        console("    [update] no delta: inputs fingerprint-identical — "
                "republishing prior arrays byte-for-byte")
        stats = {"mode": "noop", "walked_rows": 0, "ranges_rewalked": 0,
                 "ranges_total": len(ranges) * 2,
                 "cache_hits": len(ranges) * 2,
                 "prior_generation": prior.generation,
                 "wall_s": round(time.perf_counter() - t0, 3)}
        for group in ("g", "p"):
            emit("delta_walk", group=group, outcome="noop",
                 ranges_total=len(ranges), ranges_rewalked=0,
                 walked_rows=0, cache_hits=len(ranges))
        return UpdateResult(
            genes=list(prior.genes),
            embeddings=np.array(prior.embeddings, dtype=np.float32),
            biomarker_scores=(None if prior.scores is None
                              else np.array(prior.scores,
                                            dtype=np.float32)),
            biomarkers=[], km_centers=None, fingerprints=new_fp,
            acc_val=float("nan"), stats=stats)

    # ---- stage 3 under the delta plan ----------------------------------
    from g2vec_tpu.ops.walker import count_gene_freq, integrate_path_sets

    force_all = not (fp_ok and same_genes)
    path_sets, gstats = [], []
    for i, group in enumerate(["g", "p"]):
        s_k, d_k, w_k = group_csrs[i]
        prior_group = None
        if fp_ok and same_genes:
            groups = prior_fp.get("groups", [])
            prior_group = groups[i] if i < len(groups) else None
        ps, st = _group_walks(cfg, i, s_k, d_k, w_k, n_genes,
                              group_ckeys[i], prior_group, group_fps[i],
                              walk_cache, emit, group,
                              force_all=force_all)
        path_sets.append(ps)
        gstats.append(st)
        if check is not None:
            check()
    paths, labels = integrate_path_sets(path_sets[0], path_sets[1],
                                        n_genes, packed=True)
    if paths.shape[0] < 2:
        raise ValueError(
            "update: fewer than 2 distinct group-specific paths — the "
            "updated |PCC| graphs are too sparse")
    gene_freq = count_gene_freq(paths, labels, data.gene, packed=True)

    # ---- warm-start fine-tune ------------------------------------------
    import jax

    from g2vec_tpu.models.cbow import init_params
    from g2vec_tpu.train.trainer import train_cbow

    hidden = cfg.sizeHiddenlayer
    train_seed = cfg.seed if cfg.train_seed is None else cfg.train_seed
    # PR 4 contract: the seeded draw is taken at the NEW gene count
    # (layout-independent), THEN carried-over genes are overwritten
    # from the prior embedding — a new gene's row is exactly what a
    # cold run of the updated inputs would draw for it.
    base = init_params(jax.random.key(train_seed), n_genes, hidden)
    w_ih = np.array(base.w_ih, dtype=np.float32)
    w_ho = np.array(base.w_ho, dtype=np.float32)
    carried = 0
    if int(prior.embeddings.shape[1]) == hidden:
        prior_idx = prior.gene_index
        old_rows = np.fromiter(
            (prior_idx.get(g, -1) for g in data.gene),
            dtype=np.int64, count=n_genes)
        have = old_rows >= 0
        w_ih[have] = np.asarray(prior.embeddings, dtype=np.float32)[
            old_rows[have]]
        carried = int(np.count_nonzero(have))
    eff_epochs = int(epochs) if epochs else max(3, cfg.epoch // 4)
    console(f"    [update] warm start: {carried}/{n_genes} rows carried "
            f"from {prior.generation or 'flat bundle'}; fine-tune "
            f"{eff_epochs} epochs")
    result = train_cbow(
        paths, labels, packed_genes=n_genes, hidden=hidden,
        learning_rate=cfg.learningRate, max_epochs=eff_epochs,
        val_fraction=cfg.val_fraction,
        decision_threshold=cfg.decision_threshold,
        compute_dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
        seed=train_seed, check=check, warm_start=(w_ih, w_ho),
        row_bucket=FINE_TUNE_ROW_BUCKET)

    # ---- stages 5-6: L-groups + prognostic rescoring -------------------
    from g2vec_tpu.analysis import (biomarker_scores_device,
                                    find_lgroups_device, freq_index,
                                    top_biomarkers)

    emb = np.asarray(result.w_ih, dtype=np.float32)
    lgroup_dev, km_centers_dev = find_lgroups_device(
        emb, freq_index(data.gene, gene_freq),
        key=jax.random.key(cfg.kmeans_seed), k=cfg.n_lgroups,
        compat_tiebreak=cfg.compat_lgroup_tiebreak,
        iters=cfg.kmeans_iters, return_centers=True)
    labels_np = np.asarray(data.label)
    scores2 = np.asarray(biomarker_scores_device(
        emb, data.expr[labels_np == 0], data.expr[labels_np == 1],
        lgroup_dev, cfg.score_mix))
    lgroup_idx = np.asarray(lgroup_dev)
    biomarkers, _ = top_biomarkers(scores2, lgroup_idx, data.gene,
                                   cfg.numBiomarker)

    walked = sum(st["walked_rows"] for st in gstats)
    rewalked = sum(st["ranges_rewalked"] for st in gstats)
    mode = "bootstrap" if any(st["outcome"] in ("bootstrap", "full")
                              for st in gstats) else (
        "expr_only" if rewalked == 0 else "delta")
    stats = {"mode": mode, "walked_rows": walked,
             "ranges_rewalked": rewalked,
             "ranges_total": sum(st["ranges_total"] for st in gstats),
             "cache_hits": sum(st["cache_hits"] for st in gstats),
             "carried_rows": carried, "n_genes": n_genes,
             "epochs": eff_epochs, "stop_epoch": result.stop_epoch,
             "prior_generation": prior.generation,
             "wall_s": round(time.perf_counter() - t0, 3)}
    return UpdateResult(
        genes=list(data.gene), embeddings=emb,
        biomarker_scores=scores2, biomarkers=list(biomarkers),
        km_centers=np.asarray(km_centers_dev, dtype=np.float32),
        fingerprints=new_fp, acc_val=float(result.acc_val),
        stats=stats)


def within_band(acc_a: float, acc_b: float,
                biomarkers_a: Sequence[str],
                biomarkers_b: Sequence[str]) -> Tuple[bool, dict]:
    """The PR 7 statistical band check shared by bench and tests:
    |dACC| <= BAND_DACC and top-N biomarker overlap >= BAND_OVERLAP."""
    a, b = set(biomarkers_a), set(biomarkers_b)
    overlap = len(a & b) / max(len(a), 1)
    dacc = abs(float(acc_a) - float(acc_b))
    return (dacc <= BAND_DACC and overlap >= BAND_OVERLAP), \
        {"dacc": round(dacc, 4), "overlap": round(overlap, 4)}
