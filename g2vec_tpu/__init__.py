"""g2vec_tpu — a TPU-native framework for network-based cancer-biomarker discovery.

A brand-new JAX/XLA implementation of the capabilities of mathcom/G2Vec
(J.H. Choi et al., "G2Vec: Distributed gene representations for identification
of cancer prognostic genes", Scientific Reports 8.1 (2018)).

The reference (/root/reference/G2Vec.py) is a single-file CPU NumPy/TF1 tool.
This package re-designs the same seven-stage pipeline TPU-first:

- L0 config/CLI           -> :mod:`g2vec_tpu.config`
- L1 data IO              -> :mod:`g2vec_tpu.io` (+ native C++ in
  :mod:`g2vec_tpu.native`)
- L2 preprocess           -> :mod:`g2vec_tpu.preprocess`
- L3 graph + random walks -> :mod:`g2vec_tpu.ops.graph`,
  :mod:`g2vec_tpu.ops.walker` (native CPU twin:
  :mod:`g2vec_tpu.ops.host_walker`)
- L4 trainer (CBOW)       -> :mod:`g2vec_tpu.models.cbow`, :mod:`g2vec_tpu.train`
- L5 analysis             -> :mod:`g2vec_tpu.ops.stats`, :mod:`g2vec_tpu.ops.kmeans`
- L6 output writers       -> :mod:`g2vec_tpu.io.writers`
- parallelism             -> :mod:`g2vec_tpu.parallel`

This module intentionally avoids importing jax at package-import time so that
callers (CLI, tests) can configure platform/env first; ``g2vec_tpu.run`` is
therefore a lazy attribute (it resolves to :func:`g2vec_tpu.pipeline.run`
on first access, which is when jax loads).
"""

__version__ = "0.3.0"

from g2vec_tpu.config import G2VecConfig  # noqa: F401  (jax-free)


def __getattr__(name: str):
    if name == "run":
        from g2vec_tpu.pipeline import run
        return run
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
