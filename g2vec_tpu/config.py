"""L0 — configuration and CLI.

Mirrors the reference CLI exactly (ref: G2Vec.py:505-518): four positional
arguments plus ``-p/-r/-s/-e/-l/-n`` options with the same defaults, and adds
framework-level flags (seed, precision, mesh, profiling, checkpointing).

The reference's hardcoded "silent config" constants (ref: G2Vec.py:389 PCC
threshold 0.5, :220 80/20 split, :262 max epochs, :254 display step, :169
k-means k=3/random_state=0, :249 decision threshold, :102 score mix, :234-235
init std) are all named fields here.

Quirks resolved (documented in SURVEY.md §7):
- ``--epoch`` is HONORED here (the reference parses it but hardcodes
  ``range(500)``, ref: G2Vec.py:262 vs :515).
- ``--compat-lgroup-tiebreak`` reproduces the reference's degenerate good/poor
  cluster vote (ref: G2Vec.py:186-189, list-vs-int comparison bug).
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class G2VecConfig:
    """Full configuration for a g2vec_tpu run.

    Field names for the reference-compatible options keep the reference's
    camelCase spelling so CLI round-tripping is obvious.
    """

    # ---- positional (ref: G2Vec.py:508-511) ----
    expression_file: str = ""
    clinical_file: str = ""
    network_file: str = ""
    result_name: str = "result"

    # ---- reference options (ref: G2Vec.py:512-517) ----
    lenPath: int = 80                # max random-walk length
    numRepetition: int = 10          # walks started from every gene, per group
    sizeHiddenlayer: int = 128       # embedding width
    epoch: int = 500                 # max epochs (honored, unlike the reference)
    learningRate: float = 0.005      # Adam lr
    numBiomarker: int = 50           # top-N per L-group

    # ---- silent constants promoted to config ----
    pcc_threshold: float = 0.5       # edge kept iff |PCC| > threshold (ref: G2Vec.py:389)
    val_fraction: float = 0.2        # hold-out fraction (ref: G2Vec.py:220)
    display_step: int = 5            # epoch log cadence (ref: G2Vec.py:254)
    n_lgroups: int = 3               # k-means k (ref: G2Vec.py:169)
    kmeans_seed: int = 0             # ref: random_state=0 (G2Vec.py:169)
    kmeans_iters: int = 300          # Lloyd iterations cap (sklearn default)
    decision_threshold: float = 0.5  # sigmoid(O) > t (ref: G2Vec.py:249)
    score_mix: float = 0.5           # gene score = mix*d + (1-mix)*t (ref: G2Vec.py:102)

    # ---- new framework flags ----
    seed: int = 0                    # global PRNG seed (reference is unseeded)
    train_seed: Optional[int] = None  # trainer split/init seed; None = seed.
                                     # Splitting it from the walk seed lets a
                                     # validation sweep re-train under fresh
                                     # splits/inits while REUSING one walk
                                     # product (the batch engine's amortized
                                     # seed sweep — batch/engine.py)
    patient_subsample: float = 0.0   # fraction of patients kept per label
                                     # class (stratified, seeded; 0 = off).
                                     # The paper validates biomarkers over
                                     # patient resamples; this makes one
                                     # resample a first-class run config
    subsample_seed: int = 0          # PRNG seed for --patient-subsample
    subsample_mode: str = "fraction"  # cohort derivation: "fraction" keeps a
                                     # seeded stratified subset without
                                     # replacement; "bootstrap" DRAWS the
                                     # same count per class WITH replacement
                                     # (a stability resample — fraction 0
                                     # means full class size); "fold" trains
                                     # on every fold except cv_fold of a
                                     # seeded stratified cv_folds partition
    cv_folds: int = 0                # stratified partition size for
                                     # subsample_mode="fold" (0 otherwise)
    cv_fold: int = 0                 # held-out fold index in [0, cv_folds)
    permute_seed: Optional[int] = None  # permutation-null draw: shuffle the
                                     # patient labels with this seed for the
                                     # stage-6 prognostic scoring ONLY —
                                     # walks, graphs and training keep the
                                     # observed labels, so every null
                                     # replicate shares one walk product
                                     # (None = off)
    compat_lgroup_tiebreak: bool = False
    compute_dtype: str = "bfloat16"  # matmul dtype on TPU ("float32" for parity tests)
    param_dtype: str = "float32"
    walker_batch: int = 0            # walkers per device launch; 0 = auto-sized
                                     # by the HBM working-set model
                                     # (ops.walker.auto_walker_batch)
    walker_hbm_budget: int = 0       # device bytes of per-walker state the
                                     # auto-sizer may plan for (tables are
                                     # separate, launch-invariant residents);
                                     # 0 = ops.walker.WALKER_HBM_BUDGET (4 GiB)
    walker_backend: str = "auto"     # "auto": host-walks-chip-trains —
                                     # the threaded C++ CSR sampler when
                                     # available (multi-process runs shard
                                     # the walker axis across hosts and
                                     # allgather; backend agreement is
                                     # collective), else the JAX lockstep
                                     # walker (measured basis:
                                     # ops/backend.py). "device"/"native"
                                     # pin a sampler; both run the SAME
                                     # splitmix64 walk — device rows are
                                     # byte-identical to the C++ sampler's
                                     # (ops/device_walker.py parity
                                     # contract), so goldens, walk-cache
                                     # entries, and bands transfer between
                                     # backends unchanged
    sampler_threads: int = 0         # host cores for the native sampler's
                                     # thread pool (0 = all cores; output is
                                     # bit-identical at ANY count — streams
                                     # are keyed by global walker index)
    overlap: bool = True             # overlapped stage execution
                                     # (parallel/overlap.py): group walks run
                                     # concurrently and the trainer/kmeans
                                     # compiles warm in the background during
                                     # stage 3; never changes results
    fused_eval: bool = True          # fold the val-split eval forward into
                                     # the chunk body's grad pass (one fused
                                     # program per epoch; --no-fused-eval
                                     # restores the split grad+eval shape).
                                     # float32 history is bitwise-identical
                                     # either way (trainer.py parity contract)
    epoch_superstep: int = 1         # epochs unrolled per while_loop
                                     # iteration in the chunk program (K>=1);
                                     # amortizes per-iteration dispatch/cond
                                     # overhead, early stop still lands ON
                                     # the dip
    train_mode: str = "full"         # "full": the reference's full-batch
                                     # trainer (bitwise-golden contract).
                                     # "streaming": walk shards stream from
                                     # the sampler pool through a bounded
                                     # host ring into double-buffered device
                                     # prefetch; minibatch SGD starts before
                                     # sampling finishes and peak host path
                                     # memory is O(shard x depth), not
                                     # O(total paths). Statistical contract
                                     # (val-ACC parity band + biomarker
                                     # overlap), NOT bitwise vs full
                                     # (train/stream.py)
    shard_paths: int = 0             # rows per streaming walk shard, both
                                     # groups combined (0 = auto ~4096);
                                     # also the minibatch size — shards are
                                     # the matrix-multiply-shaped batches
                                     # of arXiv:1611.06172
    prefetch_depth: int = 2          # bounded host shard-ring depth; the
                                     # producer blocks (backpressure) when
                                     # this many shards wait unconsumed.
                                     # Peak host path memory ~= shard x
                                     # (depth + 2 in-flight)
    device_feed: bool = False        # fuse the device walker into the
                                     # streaming trainer: epoch 0 samples
                                     # each shard ON DEVICE and feeds the
                                     # minibatch step device-resident — no
                                     # host ring, no per-shard H2D (spool
                                     # still written, asynchronously, for
                                     # epoch 1..N replay + durability).
                                     # Requires --train-mode streaming +
                                     # --walker-backend device. Outputs
                                     # byte-identical to the ring feed
                                     # (train/stream.py)
    stream_patience: int = 5         # streaming early stop: epochs without
                                     # a strict val-ACC improvement before
                                     # stopping (1 = the full-batch
                                     # first-dip rule; minibatch epochs
                                     # jitter, so the default widens it)
    graph_shards: int = 0            # million-node scale-out (parallel/
                                     # shard.py): cut the streaming shard
                                     # sequence into this many start-gene
                                     # partitions; each is SAMPLED by one
                                     # rank and exchanged to the rest over
                                     # the chunked KV transport (0 = every
                                     # rank samples everything)
    embed_shards: int = 0            # split the [G, H] embedding by a
                                     # byte-aligned gene range per rank
                                     # (must equal the process count); the
                                     # per-rank cap that fits graphs whose
                                     # full table exceeds one host. 0 = off
    walk_starts: int = 0             # cap the number of start genes per
                                     # group (evenly spaced subset; 0 =
                                     # every gene, the reference walk
                                     # volume — infeasible at 1M nodes)
    edge_partition: str = "off"      # partition the CSR itself by owner
                                     # gene range (parallel/shard.py):
                                     # each rank loads/holds only its own
                                     # rows' edges — the last single-host
                                     # graph cap. Boundary walks:
                                     # "handoff" ships suspended walk
                                     # state to the owner rank; "halo"
                                     # also replicates 1-hop boundary
                                     # rows so most walks finish locally
                                     # (byte-identical outputs either
                                     # way). "off" = full CSR per rank
    stream_eval_rows: int = 0        # streaming val/probe buffer row cap
                                     # (0 = the 4096 default; each row is
                                     # ceil(G/8) bytes, so big-G runs may
                                     # need it smaller)
    donate_state: bool = True        # donate the (params, opt_state,
                                     # snapshot, history) carry to the chunk
                                     # program so Adam's fp32 read/write set
                                     # updates in place instead of
                                     # double-buffering in HBM
    kernel_autotune: bool = False    # measure packed-kernel tile plans at
                                     # this run's exact shapes instead of
                                     # trusting the VMEM-model heuristic
                                     # (persisted under --cache-dir's
                                     # autotune tier)
    mesh_shape: Optional[Tuple[int, int]] = None  # (data, model); None = single device
    platform: Optional[str] = None   # force jax platform (e.g. "cpu")
    profile_dir: Optional[str] = None
    compilation_cache: Optional[str] = None  # persistent XLA cache dir: repeat
                                     # runs skip the ~20-40s TPU compiles that
                                     # dominate a cold pipeline's wall clock
    cache_dir: Optional[str] = None  # one root for BOTH persistent tiers:
                                     # <dir>/xla (the XLA compilation cache,
                                     # unless --compilation-cache overrides)
                                     # and <dir>/walks (stage-3 walk
                                     # artifacts — g2vec_tpu/cache.py)
    walk_cache: bool = True          # the walk-artifact tier (only active
                                     # with --cache-dir; --no-walk-cache
                                     # disables it alone)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 25       # epochs between trainer checkpoints
                                     # (also the device chunk size while
                                     # checkpointing — a chunk boundary is
                                     # a save point)
    resume: bool = False
    # "single": one gathered npz (process-0 write, broadcast restore; dir
    # need not be shared). "sharded": orbax OCDBT per-process shards (no
    # full-state gather ever; dir MUST be shared across hosts).
    checkpoint_layout: str = "single"
    metrics_jsonl: Optional[str] = None
    use_native_io: bool = True       # use the C++ TSV reader when available
    debug_nans: bool = False
    emit_inventory: bool = False     # also publish the binary query-plane
                                     # bundle <RESULT_NAME>_inventory/
                                     # (float32 embeddings + norms +
                                     # scores + gene table + sha256
                                     # manifest — io/writers.py), so an
                                     # offline run is servable by
                                     # pointing `g2vec serve
                                     # --inventory-dir` at its directory
    ann_nlist: int = 0               # IVF list count for the bundle's ANN
                                     # index: 0 auto (~sqrt(G) past the
                                     # row floor), >0 forced, <0 disabled
                                     # (ops/ann.resolve_nlist)

    # ---- resilience (resilience/) ----
    supervise: bool = False          # wrap the run in the auto-resume
                                     # supervisor (bounded retries, backoff,
                                     # re-enter via --resume)
    supervise_retries: int = 3       # retries after the first failure
    supervise_backoff: float = 1.0   # backoff base seconds (doubles/retry)
    fault_plan: Optional[str] = None  # injection spec, e.g.
                                     # "stage=train,epoch=40,kind=crash"
                                     # (resilience/faults.py docstring)

    # ---- fleet resilience (resilience/fleet.py) ----
    fleet_size: int = 0              # >0: launch/supervise this many ranks
                                     # with degraded-mesh resume (0 = off)
    fleet_devices_per_rank: int = 0  # virtual/local devices per rank
                                     # (0 = mesh size / fleet_size)
    fleet_liveness_dir: Optional[str] = None  # heartbeat/liveness files
    fleet_heartbeat_interval: float = 1.0  # seconds between beats
    fleet_watchdog_deadline: float = 0.0   # collective timeout (0 = block)
    fleet_straggler_factor: float = 0.0    # warn when a rank exceeds this
                                     # x median stage time (0 = off)

    # ---- batch execution engine (batch/engine.py) ----
    manifest: Optional[str] = None   # JSON run manifest: a list of variant
                                     # objects (seed/train_seed/kmeans_seed/
                                     # learningRate/epoch/patient_subsample/
                                     # subsample_seed/name overrides of this
                                     # base config); the engine plans them
                                     # into shape-bucketed lanes and runs
                                     # each bucket as one batched device
                                     # program
    batch_seeds: int = 0             # --seeds N: generate an N-variant
                                     # seed-sweep manifest (train_seed and
                                     # kmeans_seed vary, the WALK seed stays
                                     # fixed so all lanes share one stage-3
                                     # product; 0 = off)
    lanes: int = 8                   # max lanes batched into one vmapped
                                     # trainer program (a bucket larger than
                                     # this splits into chunks)

    # ---- statistical scenario engine (stats/) ----
    scenario: Optional[str] = None   # bootstrap|permutation|cv: expand this
                                     # base config into a seeded replicate
                                     # manifest, execute it as engine lanes,
                                     # and reduce the per-replicate outputs
                                     # into <RESULT_NAME>_stability.txt
    replicates: int = 0              # replicate count for
                                     # scenario=bootstrap|permutation
    folds: int = 0                   # fold count for scenario=cv (K >= 2)
    scenario_seed: int = 0           # root of the scenario seed-derivation
                                     # tree (stats/plan.py): every replicate
                                     # seed is a stable hash of
                                     # (root, index, role)

    # ---- multi-host (parallel/distributed.py) ----
    distributed: bool = False        # join the multi-process JAX runtime
    coordinator: Optional[str] = None    # host:port of process 0 (or env/auto)
    process_id: Optional[int] = None
    num_processes: Optional[int] = None

    def validate(self) -> None:
        if self.lenPath < 1:
            raise ValueError(f"lenPath must be >= 1, got {self.lenPath}")
        if self.numRepetition < 1:
            raise ValueError(f"numRepetition must be >= 1, got {self.numRepetition}")
        if self.sizeHiddenlayer < 1:
            raise ValueError(f"sizeHiddenlayer must be >= 1, got {self.sizeHiddenlayer}")
        if self.epoch < 1:
            raise ValueError(f"epoch must be >= 1, got {self.epoch}")
        if self.learningRate <= 0.0:
            raise ValueError(f"learningRate must be > 0, got {self.learningRate}")
        if self.numBiomarker < 1:
            raise ValueError(f"numBiomarker must be >= 1, got {self.numBiomarker}")
        if self.walker_batch < 0:
            raise ValueError(f"walker_batch must be >= 0, got {self.walker_batch}")
        if self.walker_hbm_budget < 0:
            raise ValueError(
                f"walker_hbm_budget must be >= 0, got {self.walker_hbm_budget}")
        if self.mesh_shape is not None and any(d < 1 for d in self.mesh_shape):
            raise ValueError(f"mesh axes must be >= 1, got {self.mesh_shape}")
        if self.n_lgroups < 3:
            raise ValueError(
                f"n_lgroups must be >= 3 (good/poor/other), got {self.n_lgroups}")
        if self.display_step < 1:
            raise ValueError(f"display_step must be >= 1, got {self.display_step}")
        if not (0.0 < self.decision_threshold < 1.0):
            raise ValueError(
                f"decision_threshold must be in (0,1), got {self.decision_threshold}")
        if not (0.0 < self.val_fraction < 1.0):
            raise ValueError(f"val_fraction must be in (0,1), got {self.val_fraction}")
        if not (0.0 <= self.pcc_threshold < 1.0):
            raise ValueError(f"pcc_threshold must be in [0,1), got {self.pcc_threshold}")
        if self.compute_dtype not in ("bfloat16", "float32"):
            raise ValueError(f"compute_dtype must be bfloat16|float32, got {self.compute_dtype}")
        if self.param_dtype not in ("bfloat16", "float32"):
            raise ValueError(f"param_dtype must be bfloat16|float32, got {self.param_dtype}")
        if self.walker_backend not in ("auto", "device", "native"):
            raise ValueError(
                f"walker_backend must be auto|device|native, "
                f"got {self.walker_backend}")
        if self.epoch_superstep < 1:
            raise ValueError(
                f"epoch_superstep must be >= 1, got {self.epoch_superstep}")
        if self.train_mode not in ("full", "streaming"):
            raise ValueError(
                f"train_mode must be full|streaming, got {self.train_mode}")
        if self.shard_paths < 0:
            raise ValueError(
                f"shard_paths must be >= 0 (0 = auto), got {self.shard_paths}")
        if 0 < self.shard_paths < 4:
            raise ValueError(
                f"shard_paths must be >= 4 (2 per group, and the per-shard "
                f"split needs both sides non-empty), got {self.shard_paths}")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}")
        if self.stream_patience < 1:
            raise ValueError(
                f"stream_patience must be >= 1, got {self.stream_patience}")
        if self.device_feed:
            if self.train_mode != "streaming":
                raise ValueError(
                    "--device-feed fuses device sampling into the "
                    "STREAMING trainer; add --train-mode streaming")
            if self.walker_backend != "device":
                raise ValueError(
                    "--device-feed samples shards on device; add "
                    "--walker-backend device")
            if self.graph_shards or self.embed_shards:
                raise ValueError(
                    "--device-feed does not compose with "
                    "--graph-shards/--embed-shards yet — the sharded "
                    "trainer exchanges sampled shards over the KV "
                    "transport, which is a host path")
        if self.train_mode == "streaming":
            if self.walker_backend == "device" and (
                    self.graph_shards or self.embed_shards):
                raise ValueError(
                    "sharded streaming (--graph-shards/--embed-shards) "
                    "needs the native sampler's thread pool per rank; "
                    "--walker-backend device does not compose")
            sharded = bool(self.graph_shards or self.embed_shards)
            # The sharded mode (ROADMAP item 2) IS streaming x
            # distributed: --graph-shards/--embed-shards open that gate.
            # fleet/mesh stay closed — the sharded trainer coordinates
            # over the KV transport, not a device mesh.
            gates = [(self.fleet_size, "--fleet-size"),
                     (self.mesh_shape, "--mesh")]
            if not sharded:
                gates.insert(0, (self.distributed, "--distributed"))
            for flag, name in gates:
                if flag:
                    raise ValueError(
                        f"--train-mode streaming does not compose with "
                        f"{name} yet — the streaming trainer is a "
                        f"single-device minibatch loop per rank "
                        f"(--graph-shards/--embed-shards is the "
                        f"multi-process form)")
            if self.resume and not self.checkpoint_dir:
                raise ValueError(
                    "--resume with --train-mode streaming needs "
                    "--checkpoint-dir: the streaming cursor lives there")
            if self.checkpoint_dir and self.checkpoint_layout != "single":
                raise ValueError(
                    "--train-mode streaming checkpoints use the single-file "
                    "layout only (--checkpoint-layout single)")
        for field in ("graph_shards", "embed_shards", "walk_starts",
                      "stream_eval_rows"):
            if getattr(self, field) < 0:
                raise ValueError(
                    f"{field} must be >= 0 (0 = off/default), "
                    f"got {getattr(self, field)}")
        if self.graph_shards or self.embed_shards:
            if self.train_mode != "streaming":
                raise ValueError(
                    "--graph-shards/--embed-shards shard the STREAMING "
                    "trainer; add --train-mode streaming")
            if self.checkpoint_dir or self.resume:
                raise ValueError(
                    "sharded streaming does not compose with "
                    "--checkpoint-dir/--resume yet — the cursor would have "
                    "to be a consistent distributed snapshot")
            if self.manifest or self.batch_seeds:
                raise ValueError(
                    "sharded streaming does not compose with the batch "
                    "engine (--manifest/--seeds)")
            if self.supervise:
                raise ValueError(
                    "sharded streaming does not compose with --supervise "
                    "yet — a retried rank cannot rejoin the fleet's "
                    "collectives mid-run")
            if self.embed_shards and self.num_processes \
                    and self.embed_shards != self.num_processes:
                raise ValueError(
                    f"--embed-shards ({self.embed_shards}) must equal "
                    f"--num-processes ({self.num_processes}): the gene "
                    f"range is split 1:1 across ranks")
        if self.edge_partition not in ("off", "handoff", "halo"):
            raise ValueError(
                f"edge_partition must be off|handoff|halo, "
                f"got {self.edge_partition}")
        if self.edge_partition != "off":
            if self.train_mode != "streaming":
                raise ValueError(
                    "--edge-partition partitions the STREAMING trainer's "
                    "walk graph; add --train-mode streaming")
            if self.walker_backend == "device":
                raise ValueError(
                    "--edge-partition's owner-range handoff transport "
                    "still drives the native partial walker; "
                    "--walker-backend device (and --device-feed) are "
                    "refused until the handoff transport is ported to "
                    "the device sampler's suspend/resume states")
            if self.num_processes and self.num_processes > 1 \
                    and not self.graph_shards:
                raise ValueError(
                    "multi-rank --edge-partition rides the graph-sharded "
                    "producer's shard exchange; add --graph-shards")
            if self.checkpoint_dir or self.resume:
                raise ValueError(
                    "--edge-partition does not compose with "
                    "--checkpoint-dir/--resume yet — suspended cross-rank "
                    "walk state is not checkpointable")
        if self.sampler_threads < 0:
            raise ValueError(
                f"sampler_threads must be >= 0 (0 = all cores), "
                f"got {self.sampler_threads}")
        if self.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {self.checkpoint_every}")
        if self.supervise_retries < 0:
            raise ValueError(
                f"supervise_retries must be >= 0, got {self.supervise_retries}")
        if self.supervise_backoff < 0.0:
            raise ValueError(
                f"supervise_backoff must be >= 0, got {self.supervise_backoff}")
        if self.fleet_size < 0 or self.fleet_size == 1:
            raise ValueError(
                f"fleet_size must be 0 (off) or >= 2, got {self.fleet_size}")
        if self.fleet_devices_per_rank < 0:
            raise ValueError(
                f"fleet_devices_per_rank must be >= 0, "
                f"got {self.fleet_devices_per_rank}")
        if self.fleet_heartbeat_interval <= 0.0:
            raise ValueError(
                f"fleet_heartbeat_interval must be > 0, "
                f"got {self.fleet_heartbeat_interval}")
        if self.fleet_watchdog_deadline < 0.0:
            raise ValueError(
                f"fleet_watchdog_deadline must be >= 0, "
                f"got {self.fleet_watchdog_deadline}")
        if self.fleet_straggler_factor < 0.0:
            raise ValueError(
                f"fleet_straggler_factor must be >= 0, "
                f"got {self.fleet_straggler_factor}")
        if self.fleet_size and self.checkpoint_dir \
                and self.checkpoint_layout != "sharded":
            raise ValueError(
                "--fleet-size with --checkpoint-dir requires "
                "--checkpoint-layout sharded: degraded-mesh resume reshards "
                "the orbax leaves onto the survivors' mesh at load")
        if self.fleet_size and self.mesh_shape:
            total = self.mesh_shape[0] * self.mesh_shape[1]
            per = self.fleet_devices_per_rank or total // self.fleet_size
            if per * self.fleet_size != total:
                raise ValueError(
                    f"--fleet-size {self.fleet_size} cannot evenly host the "
                    f"{total}-device mesh {self.mesh_shape} "
                    f"({per} devices/rank)")
        if not (0.0 <= self.patient_subsample <= 1.0):
            raise ValueError(
                f"patient_subsample must be 0 (off) or in (0,1], "
                f"got {self.patient_subsample}")
        if self.subsample_mode not in ("fraction", "bootstrap", "fold"):
            raise ValueError(
                f"subsample_mode must be fraction|bootstrap|fold, "
                f"got {self.subsample_mode}")
        if self.subsample_mode == "fold":
            if self.cv_folds < 2:
                raise ValueError(
                    f"--subsample-mode fold needs --cv-folds >= 2, "
                    f"got {self.cv_folds}")
            if not (0 <= self.cv_fold < self.cv_folds):
                raise ValueError(
                    f"--cv-fold must be in [0, {self.cv_folds}), "
                    f"got {self.cv_fold}")
            if self.patient_subsample:
                raise ValueError(
                    "--subsample-mode fold derives the cohort from the fold "
                    "partition; --patient-subsample must be 0")
        elif self.cv_folds or self.cv_fold:
            raise ValueError(
                "--cv-folds/--cv-fold are only meaningful with "
                "--subsample-mode fold")
        if self.permute_seed is not None and self.permute_seed < 0:
            raise ValueError(
                f"--permute-seed must be >= 0, got {self.permute_seed}")
        if self.replicates < 0:
            raise ValueError(
                f"--replicates must be >= 0, got {self.replicates}")
        if self.folds < 0:
            raise ValueError(f"--folds must be >= 0, got {self.folds}")
        if self.scenario is not None:
            if self.scenario not in ("bootstrap", "permutation", "cv"):
                raise ValueError(
                    f"--scenario must be bootstrap|permutation|cv, "
                    f"got {self.scenario}")
            if self.manifest or self.batch_seeds:
                raise ValueError(
                    "--scenario IS a generated manifest; it is mutually "
                    "exclusive with --manifest/--seeds")
            if self.train_mode != "full":
                raise ValueError(
                    "--scenario executes replicates as batched full-mode "
                    "lanes; --train-mode streaming does not compose")
            if self.subsample_mode != "fraction" \
                    or self.permute_seed is not None:
                raise ValueError(
                    "--scenario derives the per-replicate cohort/"
                    "permutation axes itself; leave --subsample-mode/"
                    "--permute-seed at their defaults")
            if self.scenario == "cv":
                if self.folds < 2:
                    raise ValueError(
                        f"--scenario cv needs --folds >= 2, "
                        f"got {self.folds}")
                if self.replicates:
                    raise ValueError(
                        "--scenario cv sizes itself with --folds, not "
                        "--replicates")
                if self.patient_subsample:
                    raise ValueError(
                        "--scenario cv derives each cohort from the fold "
                        "partition; --patient-subsample must be 0")
            else:
                if self.replicates < 1:
                    raise ValueError(
                        f"--scenario {self.scenario} needs "
                        f"--replicates >= 1, got {self.replicates}")
                if self.folds:
                    raise ValueError(
                        "--folds is only meaningful with --scenario cv")
        elif self.replicates or self.folds:
            raise ValueError("--replicates/--folds need --scenario")
        if self.batch_seeds < 0:
            raise ValueError(
                f"--seeds must be >= 0, got {self.batch_seeds}")
        if self.lanes < 1:
            raise ValueError(f"--lanes must be >= 1, got {self.lanes}")
        if self.manifest and self.batch_seeds:
            raise ValueError(
                "--manifest and --seeds are mutually exclusive (a manifest "
                "already enumerates its variants)")
        if self.manifest or self.batch_seeds or self.scenario:
            for flag, name in ((self.distributed, "--distributed"),
                               (self.fleet_size, "--fleet-size"),
                               (self.supervise, "--supervise"),
                               (self.checkpoint_dir, "--checkpoint-dir"),
                               (self.resume, "--resume")):
                if flag:
                    raise ValueError(
                        f"the batch engine (--manifest/--seeds/--scenario) "
                        f"does not compose with {name} yet — run lanes as "
                        f"separate supervised jobs instead")
        if self.fault_plan:
            # Fail at config time with the offending token, not mid-run.
            from g2vec_tpu.resilience.faults import parse_plan

            parse_plan(self.fault_plan)


#: G2VecConfig fields a serve job's ``base`` object may set. Everything
#: else — device/mesh/platform choice, cache roots, checkpointing,
#: supervision, fleet/distributed wiring — is daemon infrastructure a
#: tenant must not reach through a job submission (the daemon owns the
#: device and the persistent tiers; serve/daemon.py builds the execution
#: config from ITS flags plus exactly these per-job fields).
SERVE_JOB_KEYS = (
    "expression_file", "clinical_file", "network_file", "result_name",
    "lenPath", "numRepetition", "sizeHiddenlayer", "epoch", "learningRate",
    "numBiomarker", "pcc_threshold", "val_fraction", "display_step",
    "n_lgroups", "kmeans_seed", "kmeans_iters", "decision_threshold",
    "score_mix", "seed", "train_seed", "patient_subsample",
    "subsample_seed", "subsample_mode", "cv_folds", "cv_fold",
    "permute_seed", "compat_lgroup_tiebreak", "compute_dtype",
    "param_dtype", "walker_batch", "walker_hbm_budget", "walker_backend",
    "sampler_threads", "fused_eval", "epoch_superstep", "donate_state",
    "use_native_io", "lanes",
    # Streaming trainer (train/stream.py): a tenant may pick the mode and
    # its shard/ring geometry; the daemon still owns the device. Jobs with
    # different train_mode never _join_key-match, so a streaming job
    # cannot be folded into a full-batch bucket (serve/daemon.py).
    # graph_shards/embed_shards/walk_starts/edge_partition/
    # stream_eval_rows are deliberately ABSENT: the sharded mode spans
    # processes — fleet topology is daemon infrastructure, not a per-job
    # knob.
    "train_mode", "shard_paths", "prefetch_depth", "stream_patience",
    "device_feed",
    # Streaming checkpoint cadence (shards between cursor writes). The
    # daemon owns WHERE checkpoints go (its state dir); a job may only
    # tune how often its own cursor is cut.
    "checkpoint_every")

_SERVE_JOB_REQUIRED = ("expression_file", "clinical_file", "network_file",
                       "result_name")

#: Config fields EXCLUDED from the serve job-join key: per-lane variant
#: axes (concrete on each LaneVariant by plan time, so the base default is
#: irrelevant), output/stream locations, and daemon-owned infrastructure.
#: Everything else must coincide for two jobs to share one engine batch —
#: and, in a replicated fleet, for the router to hash them onto the SAME
#: replica so shape-compatible jobs still join one warm bucket there.
SERVE_JOIN_EXCLUDE = frozenset({
    "result_name", "metrics_jsonl", "manifest", "batch_seeds",
    "seed", "train_seed", "kmeans_seed", "learningRate", "epoch",
    "patient_subsample", "subsample_seed",
    "subsample_mode", "cv_folds", "cv_fold", "permute_seed",
    "cache_dir", "compilation_cache", "profile_dir", "fault_plan"})


def serve_join_key(cfg: "G2VecConfig") -> Tuple:
    """The batch-compatibility key of a serve job's config.

    Lives here (not serve/daemon.py) because both sides of the serving
    plane need it without dragging in the engine: the daemon uses it to
    merge queued jobs into one engine batch, and the router (serve/
    router.py — a jax-free process) consistent-hashes it so compatible
    jobs from different clients land on the same warm replica.
    """
    return tuple((f.name, repr(getattr(cfg, f.name)))
                 for f in dataclasses.fields(cfg)
                 if f.name not in SERVE_JOIN_EXCLUDE)


def config_from_job(base: dict, defaults: Optional[G2VecConfig] = None
                    ) -> G2VecConfig:
    """A validated :class:`G2VecConfig` from a serve job's ``base`` dict.

    Only :data:`SERVE_JOB_KEYS` may appear; an unknown or infrastructure
    key raises ``ValueError`` naming it (a job typo must be rejected at
    admission, not die mid-batch). ``defaults`` seeds the non-job fields
    (the daemon passes its own flag-derived config so jobs inherit e.g.
    the walker backend policy it was launched with).
    """
    if not isinstance(base, dict):
        raise ValueError(
            f"job base must be an object, got {type(base).__name__}")
    unknown = sorted(set(base) - set(SERVE_JOB_KEYS))
    if unknown:
        raise ValueError(
            f"job base has unknown/forbidden key(s) {unknown}; "
            f"allowed: {sorted(SERVE_JOB_KEYS)}")
    missing = [k for k in _SERVE_JOB_REQUIRED
               if not base.get(k) or not isinstance(base.get(k), str)]
    if missing:
        raise ValueError(
            f"job base must set non-empty string(s) for {missing}")
    cfg = dataclasses.replace(defaults if defaults is not None
                              else G2VecConfig(), **base)
    cfg.validate()
    return cfg


def _version() -> str:
    from g2vec_tpu import __version__
    return __version__


def build_parser() -> argparse.ArgumentParser:
    """CLI mirroring the reference parser (ref: G2Vec.py:505-518) + new flags."""
    parser = argparse.ArgumentParser(
        prog="g2vec-tpu",
        description=(
            "g2vec_tpu is a TPU-native network-based deep learning framework for "
            "identifying prognostic gene signatures (biomarkers). "
            "Reference capabilities: mathcom/G2Vec (Sci. Reports 8.1, 2018)."
        ),
    )
    parser.add_argument("EXPRESSION_FILE", type=str,
                        help="Tab-delimited file for gene expression profiles.")
    parser.add_argument("CLINICAL_FILE", type=str,
                        help="Tab-delimited file for patient's clinical data. "
                             "LABEL=0:good prognosis and 1:poor prognosis.")
    parser.add_argument("NETWORK_FILE", type=str,
                        help="Tab-delimited file for gene interaction network.")
    parser.add_argument("RESULT_NAME", type=str,
                        help="Results are saved as 1) *_biomarkers.txt, "
                             "2) *_lgroups.txt, and 3) *_vectors.txt")
    parser.add_argument("-p", "--lenPath", type=int, default=80)
    parser.add_argument("-r", "--numRepetition", type=int, default=10)
    parser.add_argument("-s", "--sizeHiddenlayer", type=int, default=128)
    parser.add_argument("-e", "--epoch", type=int, default=500)
    parser.add_argument("-l", "--learningRate", type=float, default=0.005)
    parser.add_argument("-n", "--numBiomarker", type=int, default=50)
    # framework flags
    parser.add_argument("--version", action="version",
                        version=f"%(prog)s {_version()}")
    parser.add_argument("--seed", type=int, default=0,
                        help="Global PRNG seed (the reference is unseeded).")
    parser.add_argument("--train-seed", type=int, default=None,
                        help="Trainer split/init seed (default: --seed). "
                             "Decoupling it from the walk seed lets a "
                             "validation sweep re-train under fresh splits "
                             "while reusing one stage-3 walk product.")
    parser.add_argument("--kmeans-seed", type=int, default=0,
                        help="Stage-5 k-means seed (ref: random_state=0).")
    parser.add_argument("--patient-subsample", type=float, default=0.0,
                        metavar="FRAC",
                        help="Keep this fraction of patients per label "
                             "class (stratified, seeded by "
                             "--subsample-seed; 0 = off). One patient "
                             "resample as a first-class run config.")
    parser.add_argument("--subsample-seed", type=int, default=0)
    parser.add_argument("--subsample-mode", type=str, default="fraction",
                        choices=("fraction", "bootstrap", "fold"),
                        help="Cohort derivation. 'fraction' (default): keep "
                             "a seeded stratified --patient-subsample "
                             "subset without replacement. 'bootstrap': "
                             "DRAW the same count per label class WITH "
                             "replacement (a stability resample; fraction "
                             "0 means full class size). 'fold': train on "
                             "every fold except --cv-fold of a seeded "
                             "stratified --cv-folds partition.")
    parser.add_argument("--cv-folds", type=int, default=0, metavar="K",
                        help="Stratified partition size for "
                             "--subsample-mode fold (K >= 2; all folds of "
                             "one partition share --subsample-seed).")
    parser.add_argument("--cv-fold", type=int, default=0, metavar="I",
                        help="Held-out fold index in [0, K) for "
                             "--subsample-mode fold; the run trains on the "
                             "other K-1 folds.")
    parser.add_argument("--permute-seed", type=int, default=None,
                        help="Permutation-null draw: shuffle patient labels "
                             "with this seed for the stage-6 prognostic "
                             "scoring ONLY — walks, graphs and training "
                             "keep the observed labels, so null replicates "
                             "share one walk product (default: off).")
    parser.add_argument("--manifest", type=str, default=None, metavar="JSON",
                        help="Batch run manifest: a JSON list of variant "
                             "objects (seed/train_seed/kmeans_seed/"
                             "learningRate/epoch/patient_subsample/"
                             "subsample_seed/name overrides of this base "
                             "config). The batch engine plans the variants "
                             "into shape-bucketed lanes and executes each "
                             "bucket as one batched device program; every "
                             "lane's outputs are bitwise identical to the "
                             "same config run solo.")
    parser.add_argument("--seeds", type=int, default=0, metavar="N",
                        dest="batch_seeds",
                        help="Generate an N-variant seed-sweep manifest "
                             "(train_seed/kmeans_seed vary; the walk seed "
                             "stays fixed so all lanes amortize one "
                             "stage-3 walk product).")
    parser.add_argument("--lanes", type=int, default=8, metavar="B",
                        help="Max lanes batched into one vmapped trainer "
                             "program (default 8); larger buckets split.")
    parser.add_argument("--scenario", type=str, default=None,
                        choices=("bootstrap", "permutation", "cv"),
                        help="Statistical scenario engine (stats/): expand "
                             "this base config into a seeded replicate "
                             "manifest — bootstrap patient resamples, "
                             "label-permutation nulls, or stratified CV "
                             "folds — execute it as shape-bucketed engine "
                             "lanes, and reduce the per-replicate outputs "
                             "into RESULT_NAME_stability.txt.")
    parser.add_argument("--replicates", type=int, default=0, metavar="N",
                        help="Replicate count for --scenario "
                             "bootstrap|permutation.")
    parser.add_argument("--folds", type=int, default=0, metavar="K",
                        help="Fold count for --scenario cv (K >= 2; one "
                             "lane per held-out fold).")
    parser.add_argument("--scenario-seed", type=int, default=0,
                        help="Root of the scenario seed-derivation tree; "
                             "every replicate's seed is a stable hash of "
                             "(root, index, role), so a scenario rerun is "
                             "byte-identical end to end.")
    parser.add_argument("--pcc-threshold", type=float, default=0.5)
    parser.add_argument("--val-fraction", type=float, default=0.2)
    parser.add_argument("--compat-lgroup-tiebreak", action="store_true",
                        help="Reproduce the reference's degenerate L-group vote.")
    parser.add_argument("--compute-dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--walker-batch", type=int, default=0,
                        help="Walkers per device launch (0 = auto-sized "
                             "against --walker-hbm-budget).")
    parser.add_argument("--walker-backend", type=str, default="auto",
                        choices=("auto", "device", "native"),
                        help="Path sampler. 'auto' (default) routes walks "
                             "to the threaded C++ CSR sampler whenever it "
                             "is available — multi-process runs shard the "
                             "walker axis across hosts and allgather the "
                             "packed rows — and to the JAX lockstep "
                             "walker otherwise (host-walks-chip-trains; "
                             "measured basis in ARCHITECTURE.md). "
                             "'device'/'native' pin one.")
    parser.add_argument("--walker-hbm-budget", type=int, default=0,
                        help="Device bytes the walker auto-sizer may plan "
                             "for (0 = 4 GiB default).")
    parser.add_argument("--sampler-threads", type=int, default=0,
                        help="Host cores for the native sampler's thread "
                             "pool (0 = all cores). Walk output is "
                             "bit-identical at any count — per-walker PRNG "
                             "streams are keyed by global walker index.")
    parser.add_argument("--train-mode", type=str, default="full",
                        choices=("full", "streaming"),
                        help="full (default): the reference's full-batch "
                             "trainer — the bitwise-golden path. "
                             "streaming: fixed-size walk shards stream "
                             "from the sampler pool through a bounded "
                             "host ring into device prefetch buffers; "
                             "minibatch-SGD training starts before "
                             "sampling finishes and peak host path "
                             "memory is O(shard x depth). Statistical "
                             "contract: val-ACC parity band + biomarker "
                             "overlap vs full-batch, not bitwise.")
    parser.add_argument("--shard-paths", type=int, default=0, metavar="N",
                        help="Rows per streaming walk shard / minibatch "
                             "(both groups combined; 0 = auto ~4096). "
                             "Same seed + same shard size => bitwise-"
                             "identical streaming trajectories at any "
                             "thread count or ring depth.")
    parser.add_argument("--prefetch-depth", type=int, default=2,
                        metavar="D",
                        help="Bounded host shard-ring depth for "
                             "--train-mode streaming (default 2); the "
                             "sampler blocks when D shards wait "
                             "unconsumed (backpressure).")
    parser.add_argument("--device-feed", action="store_true",
                        help="Fuse device sampling into the streaming "
                             "trainer: epoch 0 shards are sampled ON "
                             "DEVICE and consumed device-resident (no "
                             "host ring, no per-shard H2D; spool written "
                             "asynchronously for replay). Requires "
                             "--train-mode streaming --walker-backend "
                             "device. Outputs byte-identical to the ring "
                             "feed.")
    parser.add_argument("--stream-patience", type=int, default=5,
                        metavar="K",
                        help="Streaming early stop: stop after K epochs "
                             "without a strict val-ACC improvement and "
                             "return the best epoch's snapshot (default "
                             "5; 1 = the full-batch first-dip rule).")
    parser.add_argument("--graph-shards", type=int, default=0, metavar="N",
                        help="Scale-out: partition walk sampling into N "
                             "start-gene ranges; each walk shard is sampled "
                             "once by its owner rank and published to peers "
                             "over the chunked KV transport (0 = off, every "
                             "rank samples everything). Requires "
                             "--train-mode streaming; multi-rank runs also "
                             "need --distributed.")
    parser.add_argument("--embed-shards", type=int, default=0, metavar="R",
                        help="Scale-out: shard the [G, H] embedding table "
                             "across R ranks by byte-aligned gene range; "
                             "the hidden activation is allreduced once per "
                             "step and stages 5-6 run on the local slice "
                             "(0 = off). R must equal the process count; "
                             "single-rank sharded runs are byte-identical "
                             "to the unsharded path.")
    parser.add_argument("--walk-starts", type=int, default=0, metavar="W",
                        help="Cap the walk volume to W evenly spaced start "
                             "genes instead of all G (0 = all genes, the "
                             "previous behavior exactly). Million-node "
                             "graphs need this: full walk volume scales "
                             "with G x reps x len.")
    parser.add_argument("--edge-partition", type=str, default="off",
                        choices=("off", "handoff", "halo"),
                        help="Partition the CSR itself by owner gene range: "
                             "each rank streams only its own rows' edges "
                             "from disk (never the full edge list). "
                             "Boundary-crossing walks: 'handoff' ships the "
                             "suspended walk state to the owner rank; "
                             "'halo' also replicates 1-hop boundary rows "
                             "so most walks finish locally. Outputs are "
                             "byte-identical either way. Requires "
                             "--train-mode streaming; multi-rank runs also "
                             "need --graph-shards (default off).")
    parser.add_argument("--stream-eval-rows", type=int, default=0,
                        metavar="M",
                        help="Rows kept for the streaming val split "
                             "(0 = auto cap). Bounds eval memory on "
                             "million-node runs.")
    parser.add_argument("--no-fused-eval", action="store_true",
                        help="Keep the val-split eval as its own per-epoch "
                             "program instead of riding the grad pass's "
                             "forward. float32 results are bitwise-identical "
                             "either way; this is an attribution/debugging "
                             "switch.")
    parser.add_argument("--epoch-superstep", type=int, default=1,
                        metavar="K",
                        help="Epochs unrolled per device-loop iteration in "
                             "the trainer chunk program (default 1). K>=8 "
                             "amortizes the while_loop's per-iteration "
                             "overhead; the early stop still exits on the "
                             "dip epoch.")
    parser.add_argument("--no-donate", action="store_true",
                        help="Do not donate the trainer carry buffers to "
                             "the chunk program (keeps Adam's fp32 state "
                             "double-buffered in HBM; attribution switch).")
    parser.add_argument("--kernel-autotune", action="store_true",
                        help="Sweep the packed kernel's legal tile plans at "
                             "this run's exact matmul shapes and use the "
                             "measured best instead of the heuristic "
                             "(persisted under --cache-dir/autotune so "
                             "repeat runs skip the sweep).")
    parser.add_argument("--no-overlap", action="store_true",
                        help="Disable overlapped stage execution (concurrent "
                             "group walks + background compile warming). "
                             "Results are identical either way; this is a "
                             "debugging/attribution switch.")
    parser.add_argument("--cache-dir", type=str, default=None, metavar="DIR",
                        help="Root for BOTH persistent caches: <DIR>/xla "
                             "(XLA compilation cache) and <DIR>/walks "
                             "(sha256-verified stage-3 walk artifacts — a "
                             "repeat run at the same inputs/config skips "
                             "the walks entirely).")
    parser.add_argument("--no-walk-cache", action="store_true",
                        help="Keep --cache-dir's compile tier but never "
                             "read/write walk artifacts.")
    parser.add_argument("--mesh", type=str, default=None, metavar="DATAxMODEL",
                        help="Device mesh shape, e.g. 4x2 (data x model).")
    parser.add_argument("--platform", type=str, default=None,
                        help="Force a jax platform (e.g. cpu).")
    parser.add_argument("--compilation-cache", type=str, default=None,
                        metavar="DIR",
                        help="Persistent XLA compilation cache directory; "
                             "repeat runs at the same shapes skip compiles.")
    parser.add_argument("--profile-dir", type=str, default=None,
                        help="Write a jax.profiler trace of the run here.")
    parser.add_argument("--checkpoint-dir", type=str, default=None)
    parser.add_argument("--checkpoint-every", type=int, default=25,
                        help="Epochs between trainer checkpoints "
                             "(default 25).")
    parser.add_argument("--resume", action="store_true")
    parser.add_argument("--checkpoint-layout", type=str, default="single",
                        choices=("single", "sharded"),
                        help="single: one gathered npz (dir per host OK); "
                             "sharded: orbax per-process shards, no "
                             "full-state gather (dir must be shared).")
    parser.add_argument("--metrics-jsonl", type=str, default=None,
                        help="Write structured per-stage/per-epoch metrics here.")
    parser.add_argument("--emit-inventory", action="store_true",
                        help="Also publish RESULT_NAME_inventory/ — the "
                             "query plane's binary bundle (float32 "
                             "embeddings + row norms + prognostic scores "
                             "+ gene table, sha256-manifested). Byte-"
                             "identical to what the serve daemon "
                             "publishes for the same config; `g2vec "
                             "serve --inventory-dir` makes it queryable.")
    parser.add_argument("--ann-nlist", type=int, default=0, metavar="N",
                        help="IVF list count for --emit-inventory's ANN "
                             "index: 0 (default) auto-sizes to ~sqrt(G) "
                             "once the bundle clears the row floor, N>0 "
                             "forces N lists, N<0 disables the build. "
                             "Seeded from the run's k-means centroids "
                             "when shapes permit.")
    parser.add_argument("--no-native-io", action="store_true",
                        help="Disable the C++ TSV reader.")
    parser.add_argument("--debug-nans", action="store_true")
    # resilience
    parser.add_argument("--supervise", action="store_true",
                        help="Run under the auto-resume supervisor: bounded "
                             "retries with exponential backoff; retryable "
                             "failures (preemption, OOM, worker death) "
                             "re-enter via --resume, fatal ones (bad input, "
                             "config errors) stop immediately.")
    parser.add_argument("--supervise-retries", type=int, default=3,
                        help="Retry budget for --supervise (default 3).")
    parser.add_argument("--supervise-backoff", type=float, default=1.0,
                        help="Backoff base seconds for --supervise; doubles "
                             "per retry, jittered (default 1.0).")
    parser.add_argument("--fault-plan", type=str, default=None,
                        metavar="SPEC",
                        help="Inject faults at named seams, e.g. "
                             "'stage=train,epoch=40,kind=crash' "
                             "(kinds: crash|fatal|sigkill|stall|corrupt; "
                             "equivalently env G2VEC_FAULT_PLAN).")
    # fleet resilience
    parser.add_argument("--fleet-size", type=int, default=0, metavar="N",
                        help="Launch and supervise an N-rank fleet with "
                             "degraded-mesh resume: on peer death the mesh "
                             "is re-planned over the surviving devices and "
                             "the fleet relaunches with --resume from the "
                             "sharded checkpoint (0 = off).")
    parser.add_argument("--fleet-devices-per-rank", type=int, default=0,
                        help="Devices each fleet rank hosts (0 = mesh size "
                             "/ fleet size; on --platform cpu these are "
                             "virtual devices).")
    parser.add_argument("--fleet-liveness-dir", type=str, default=None,
                        metavar="DIR",
                        help="Shared dir for per-rank heartbeat/liveness "
                             "files; enables the heartbeat thread and "
                             "watchdog blame attribution (the fleet "
                             "launcher creates one when unset).")
    parser.add_argument("--fleet-heartbeat-interval", type=float,
                        default=1.0,
                        help="Seconds between liveness beats (default 1).")
    parser.add_argument("--fleet-watchdog-deadline", type=float, default=0.0,
                        help="Seconds a blocking multihost collective may "
                             "take before PeerTimeoutError names the "
                             "missing/straggler rank(s); 0 (default) "
                             "blocks forever (legacy semantics).")
    parser.add_argument("--fleet-straggler-factor", type=float, default=0.0,
                        help="Warn (straggler_warning metrics event) when "
                             "a rank's stage time exceeds this multiple of "
                             "the fleet median; 0 disables.")
    # multi-host
    parser.add_argument("--distributed", action="store_true",
                        help="Join the multi-process JAX runtime (one process "
                             "per host; TPU pods auto-detect the topology).")
    parser.add_argument("--coordinator", type=str, default=None,
                        metavar="HOST:PORT",
                        help="Coordinator address for --distributed off-TPU "
                             "(or env G2VEC_COORDINATOR).")
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    return parser


def parse_mesh(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    if spec is None:
        return None
    try:
        d, m = spec.lower().split("x")
        return (int(d), int(m))
    except Exception as e:
        raise ValueError(f"--mesh must look like 4x2, got {spec!r}") from e


def config_from_args(argv=None) -> G2VecConfig:
    args = build_parser().parse_args(argv)
    cfg = G2VecConfig(
        expression_file=args.EXPRESSION_FILE,
        clinical_file=args.CLINICAL_FILE,
        network_file=args.NETWORK_FILE,
        result_name=args.RESULT_NAME,
        lenPath=args.lenPath,
        numRepetition=args.numRepetition,
        sizeHiddenlayer=args.sizeHiddenlayer,
        epoch=args.epoch,
        learningRate=args.learningRate,
        numBiomarker=args.numBiomarker,
        seed=args.seed,
        train_seed=args.train_seed,
        kmeans_seed=args.kmeans_seed,
        patient_subsample=args.patient_subsample,
        subsample_seed=args.subsample_seed,
        subsample_mode=args.subsample_mode,
        cv_folds=args.cv_folds,
        cv_fold=args.cv_fold,
        permute_seed=args.permute_seed,
        manifest=args.manifest,
        batch_seeds=args.batch_seeds,
        lanes=args.lanes,
        scenario=args.scenario,
        replicates=args.replicates,
        folds=args.folds,
        scenario_seed=args.scenario_seed,
        pcc_threshold=args.pcc_threshold,
        val_fraction=args.val_fraction,
        compat_lgroup_tiebreak=args.compat_lgroup_tiebreak,
        compute_dtype=args.compute_dtype,
        walker_batch=args.walker_batch,
        walker_hbm_budget=args.walker_hbm_budget,
        walker_backend=args.walker_backend,
        sampler_threads=args.sampler_threads,
        fused_eval=not args.no_fused_eval,
        train_mode=args.train_mode,
        shard_paths=args.shard_paths,
        prefetch_depth=args.prefetch_depth,
        device_feed=args.device_feed,
        stream_patience=args.stream_patience,
        graph_shards=args.graph_shards,
        embed_shards=args.embed_shards,
        walk_starts=args.walk_starts,
        edge_partition=args.edge_partition,
        stream_eval_rows=args.stream_eval_rows,
        epoch_superstep=args.epoch_superstep,
        donate_state=not args.no_donate,
        kernel_autotune=args.kernel_autotune,
        overlap=not args.no_overlap,
        mesh_shape=parse_mesh(args.mesh),
        platform=args.platform,
        profile_dir=args.profile_dir,
        compilation_cache=args.compilation_cache,
        cache_dir=args.cache_dir,
        walk_cache=not args.no_walk_cache,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
        checkpoint_layout=args.checkpoint_layout,
        metrics_jsonl=args.metrics_jsonl,
        emit_inventory=args.emit_inventory,
        ann_nlist=args.ann_nlist,
        use_native_io=not args.no_native_io,
        debug_nans=args.debug_nans,
        supervise=args.supervise,
        supervise_retries=args.supervise_retries,
        supervise_backoff=args.supervise_backoff,
        fault_plan=args.fault_plan,
        fleet_size=args.fleet_size,
        fleet_devices_per_rank=args.fleet_devices_per_rank,
        fleet_liveness_dir=args.fleet_liveness_dir,
        fleet_heartbeat_interval=args.fleet_heartbeat_interval,
        fleet_watchdog_deadline=args.fleet_watchdog_deadline,
        fleet_straggler_factor=args.fleet_straggler_factor,
        distributed=args.distributed,
        coordinator=args.coordinator,
        process_id=args.process_id,
        num_processes=args.num_processes,
    )
    cfg.validate()
    return cfg
