"""L5 — jitted k-means (k-means++ seeding + Lloyd iterations, multi-restart).

Replaces the reference's ``sklearn.cluster.KMeans(n_clusters=3,
random_state=0)`` (ref: G2Vec.py:169). Exact sklearn parity is impossible and
unnecessary: the downstream renumbering (ref: G2Vec.py:174-199) makes L-group
output invariant to cluster-label permutation, and cluster *membership* on the
well-separated embedding geometry this pipeline produces (a large blob of
never-updated rows near init plus good/poor blobs) is stable across
implementations. We match sklearn's algorithm shape instead: n_init=10
k-means++ restarts, Lloyd to convergence, best inertia wins.

All restarts run batched under one jit via vmap — on TPU this is a handful of
[G, k]-by-[k, d] distance matmuls per iteration, n_init-way parallel.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def _pairwise_sq_dists(x: jax.Array, centers: jax.Array) -> jax.Array:
    """[N, k] squared Euclidean distances (MXU-friendly: one matmul)."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)          # [N, 1]
    c2 = jnp.sum(centers * centers, axis=1)             # [k]
    xc = x @ centers.T                                  # [N, k]
    return jnp.maximum(x2 - 2.0 * xc + c2[None, :], 0.0)


def _kmeanspp_init(x: jax.Array, k: int, key: jax.Array) -> jax.Array:
    """k-means++ seeding: first center uniform, rest ~ D^2 weighting."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centers = jnp.tile(x[first], (k, 1))                # placeholder rows
    d2 = jnp.sum((x - x[first]) ** 2, axis=1)
    for j in range(1, k):                               # k is tiny and static
        key, sub = jax.random.split(key)
        # Gumbel-max sample proportional to d2 (categorical without renorm).
        logits = jnp.where(d2 > 0, jnp.log(jnp.where(d2 > 0, d2, 1.0)), -jnp.inf)
        gumbel = jax.random.gumbel(sub, (n,))
        idx = jnp.argmax(jnp.where(jnp.isneginf(logits), -jnp.inf, logits + gumbel))
        # All-zero d2 (all points identical to chosen centers): fall back to 0.
        idx = jnp.where(jnp.any(d2 > 0), idx, 0)
        centers = centers.at[j].set(x[idx])
        d2 = jnp.minimum(d2, jnp.sum((x - x[idx]) ** 2, axis=1))
    return centers


def _update_centers(onehot: jax.Array, x: jax.Array,
                    centers: jax.Array) -> jax.Array:
    """One Lloyd center update — with the EMPTY-CLUSTER path explicit.

    A cluster with no members keeps its previous center VERBATIM (no
    respawn, no perturbation — sklearn would relocate it; we deliberately
    do not, to stay one data-independent compiled program). Empty
    clusters arise systematically from degenerate inputs: with N <= k, or
    with identical rows, k-means++'s all-zero-D^2 fallback
    (:func:`_kmeanspp_init`) seeds DUPLICATE centers; ``argmin`` then
    resolves the tie to the lowest cluster index, the higher-indexed
    duplicates get zero members, and this ``where`` freezes them in
    place. That behavior is a pinned contract
    (tests/test_kmeans_lgroups.py degenerate-input battery): downstream
    L-group renumbering tolerates empty clusters, and the frozen-center
    choice keeps the program deterministic per seed.
    """
    counts = onehot.sum(axis=0)                         # [k]
    sums = onehot.T @ x                                 # [k, d]
    return jnp.where(counts[:, None] > 0,
                     sums / jnp.maximum(counts, 1.0)[:, None],
                     centers)


def _lloyd(x: jax.Array, centers0: jax.Array, iters: int
           ) -> Tuple[jax.Array, jax.Array]:
    """Fixed-iteration Lloyd's algorithm; returns (centers, inertia)."""
    k = centers0.shape[0]

    def body(centers, _):
        d2 = _pairwise_sq_dists(x, centers)             # [N, k]
        assign = jnp.argmin(d2, axis=1)                 # [N]
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)   # [N, k]
        return _update_centers(onehot, x, centers), None

    centers, _ = jax.lax.scan(body, centers0, None, length=iters)
    d2 = _pairwise_sq_dists(x, centers)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return centers, inertia


@partial(jax.jit, static_argnames=("k", "n_init", "iters"))
def kmeans(x: jax.Array, k: int, key: jax.Array, n_init: int = 10,
           iters: int = 50) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-restart k-means. Returns (labels [N] int32, centers [k,d], inertia).

    ``iters`` is a fixed budget rather than a tolerance check — data-independent
    control flow keeps the whole thing one compiled XLA program.

    Degenerate inputs are defined behavior, pinned by regression tests
    (tests/test_kmeans_lgroups.py): N <= k or all-identical rows seed
    duplicate centers through k-means++'s all-zero-D^2 fallback
    (``idx=0`` in :func:`_kmeanspp_init`); argmin ties assign members to
    the LOWEST duplicate index, the other duplicates stay empty and keep
    their center verbatim (:func:`_update_centers`). N == 0 is the one
    rejected input — there is no point to seed from.
    """
    if x.ndim != 2 or x.shape[0] < 1:
        raise ValueError(
            f"kmeans needs a non-empty [N, d] matrix, got shape {x.shape}")
    if k < 1:
        raise ValueError(f"kmeans needs k >= 1, got {k}")
    x = x.astype(jnp.float32)
    keys = jax.random.split(key, n_init)
    centers0 = jax.vmap(lambda kk: _kmeanspp_init(x, k, kk))(keys)
    centers, inertia = jax.vmap(lambda c0: _lloyd(x, c0, iters))(centers0)
    best = jnp.argmin(inertia)
    best_centers = centers[best]
    labels = jnp.argmin(_pairwise_sq_dists(x, best_centers), axis=1).astype(jnp.int32)
    return labels, best_centers, inertia[best]
