"""L5 — jitted k-means (k-means++ seeding + Lloyd iterations, multi-restart).

Replaces the reference's ``sklearn.cluster.KMeans(n_clusters=3,
random_state=0)`` (ref: G2Vec.py:169). Exact sklearn parity is impossible and
unnecessary: the downstream renumbering (ref: G2Vec.py:174-199) makes L-group
output invariant to cluster-label permutation, and cluster *membership* on the
well-separated embedding geometry this pipeline produces (a large blob of
never-updated rows near init plus good/poor blobs) is stable across
implementations. We match sklearn's algorithm shape instead: n_init=10
k-means++ restarts, Lloyd to convergence, best inertia wins.

All restarts run batched under one jit via vmap — on TPU this is a handful of
[G, k]-by-[k, d] distance matmuls per iteration, n_init-way parallel.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def _pairwise_sq_dists(x: jax.Array, centers: jax.Array) -> jax.Array:
    """[N, k] squared Euclidean distances (MXU-friendly: one matmul)."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)          # [N, 1]
    c2 = jnp.sum(centers * centers, axis=1)             # [k]
    xc = x @ centers.T                                  # [N, k]
    return jnp.maximum(x2 - 2.0 * xc + c2[None, :], 0.0)


def _kmeanspp_init(x: jax.Array, k: int, key: jax.Array) -> jax.Array:
    """k-means++ seeding: first center uniform, rest ~ D^2 weighting."""
    n = x.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centers = jnp.tile(x[first], (k, 1))                # placeholder rows
    d2 = jnp.sum((x - x[first]) ** 2, axis=1)
    for j in range(1, k):                               # k is tiny and static
        key, sub = jax.random.split(key)
        # Gumbel-max sample proportional to d2 (categorical without renorm).
        logits = jnp.where(d2 > 0, jnp.log(jnp.where(d2 > 0, d2, 1.0)), -jnp.inf)
        gumbel = jax.random.gumbel(sub, (n,))
        idx = jnp.argmax(jnp.where(jnp.isneginf(logits), -jnp.inf, logits + gumbel))
        # All-zero d2 (all points identical to chosen centers): fall back to 0.
        idx = jnp.where(jnp.any(d2 > 0), idx, 0)
        centers = centers.at[j].set(x[idx])
        d2 = jnp.minimum(d2, jnp.sum((x - x[idx]) ** 2, axis=1))
    return centers


def _update_centers(onehot: jax.Array, x: jax.Array,
                    centers: jax.Array) -> jax.Array:
    """One Lloyd center update — with the EMPTY-CLUSTER path explicit.

    A cluster with no members keeps its previous center VERBATIM (no
    respawn, no perturbation — sklearn would relocate it; we deliberately
    do not, to stay one data-independent compiled program). Empty
    clusters arise systematically from degenerate inputs: with N <= k, or
    with identical rows, k-means++'s all-zero-D^2 fallback
    (:func:`_kmeanspp_init`) seeds DUPLICATE centers; ``argmin`` then
    resolves the tie to the lowest cluster index, the higher-indexed
    duplicates get zero members, and this ``where`` freezes them in
    place. That behavior is a pinned contract
    (tests/test_kmeans_lgroups.py degenerate-input battery): downstream
    L-group renumbering tolerates empty clusters, and the frozen-center
    choice keeps the program deterministic per seed.
    """
    counts = onehot.sum(axis=0)                         # [k]
    sums = onehot.T @ x                                 # [k, d]
    return jnp.where(counts[:, None] > 0,
                     sums / jnp.maximum(counts, 1.0)[:, None],
                     centers)


def _lloyd(x: jax.Array, centers0: jax.Array, iters: int
           ) -> Tuple[jax.Array, jax.Array]:
    """Fixed-iteration Lloyd's algorithm; returns (centers, inertia)."""
    k = centers0.shape[0]

    def body(centers, _):
        d2 = _pairwise_sq_dists(x, centers)             # [N, k]
        assign = jnp.argmin(d2, axis=1)                 # [N]
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)   # [N, k]
        return _update_centers(onehot, x, centers), None

    centers, _ = jax.lax.scan(body, centers0, None, length=iters)
    d2 = _pairwise_sq_dists(x, centers)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return centers, inertia


@partial(jax.jit, static_argnames=("k", "n_init", "iters"))
def kmeans(x: jax.Array, k: int, key: jax.Array, n_init: int = 10,
           iters: int = 50) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-restart k-means. Returns (labels [N] int32, centers [k,d], inertia).

    ``iters`` is a fixed budget rather than a tolerance check — data-independent
    control flow keeps the whole thing one compiled XLA program.

    Degenerate inputs are defined behavior, pinned by regression tests
    (tests/test_kmeans_lgroups.py): N <= k or all-identical rows seed
    duplicate centers through k-means++'s all-zero-D^2 fallback
    (``idx=0`` in :func:`_kmeanspp_init`); argmin ties assign members to
    the LOWEST duplicate index, the other duplicates stay empty and keep
    their center verbatim (:func:`_update_centers`). N == 0 is the one
    rejected input — there is no point to seed from.
    """
    if x.ndim != 2 or x.shape[0] < 1:
        raise ValueError(
            f"kmeans needs a non-empty [N, d] matrix, got shape {x.shape}")
    if k < 1:
        raise ValueError(f"kmeans needs k >= 1, got {k}")
    x = x.astype(jnp.float32)
    keys = jax.random.split(key, n_init)
    centers0 = jax.vmap(lambda kk: _kmeanspp_init(x, k, kk))(keys)
    centers, inertia = jax.vmap(lambda c0: _lloyd(x, c0, iters))(centers0)
    best = jnp.argmin(inertia)
    best_centers = centers[best]
    labels = jnp.argmin(_pairwise_sq_dists(x, best_centers), axis=1).astype(jnp.int32)
    return labels, best_centers, inertia[best]


# ---------------------------------------------------------------------------
# Row-sharded k-means (ROADMAP item 2 — [G/ranks, H] embeddings)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k",))
def _local_lloyd_stats(x: jax.Array, centers: jax.Array, k: int):
    """One rank's Lloyd-iteration sufficient statistics: per-cluster
    member counts [k], member sums [k, d], and the local inertia — the
    ONLY values that must cross ranks per iteration (never the [N, d]
    rows)."""
    d2 = _pairwise_sq_dists(x, centers)
    assign = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)
    return onehot.sum(axis=0), onehot.T @ x, jnp.sum(jnp.min(d2, axis=1))


#: Rows each rank contributes to the k-means++ seeding sample. Seeding
#: must see the GLOBAL geometry — a contiguous gene range is a biased
#: slice of it (the never-updated near-init blob and the good/poor blobs
#: are not uniform over gene ids), and restarts seeded from one rank's
#: slice land in systematically different basins than the unsharded
#: program's. 4096 rows/rank keeps the gathered sample a few MB at any
#: scale while covering every rank's slice evenly.
SEED_SAMPLE_PER_RANK = 4096


def kmeans_sharded(x_local, k: int, key, *, allreduce, gather,
                   n_init: int = 10, iters: int = 50
                   ) -> Tuple[jax.Array, jax.Array, float]:
    """Distributed Lloyd over ROW-SHARDED ``x`` — each rank holds a
    disjoint ``[N_local, d]`` slice of the global matrix and only
    per-cluster sufficient statistics ([k, d] sums, [k] counts, scalar
    inertia) ever cross ranks. Returns ``(labels_local [N_local] int32,
    centers [k, d], inertia)``; centers/inertia are replicated (every
    rank folds the identical rank-ordered reduction), labels cover the
    local rows only.

    Collective-injection seam: ``allreduce(name, np_array) -> np_array``
    sums same-shape host arrays deterministically across ranks and
    ``gather(name, np_array) -> np_array`` concatenates per-rank arrays
    in rank order on every rank (parallel/shard.ShardContext provides
    both; keeping them as callables keeps ops/ free of any transport
    dependency and makes the math unit-testable single-process with
    identity lambdas).

    Semantics vs :func:`kmeans`: the SAME multi-restart recipe (n_init
    k-means++ seedings from split keys, fixed-``iters`` Lloyd,
    empty clusters keep their center verbatim, best inertia wins).
    Seeding draws from one rank-order gather of evenly-spaced rows
    (``SEED_SAMPLE_PER_RANK`` per rank — the full matrix at small N, a
    global stratified sample at scale) and every rank computes the
    IDENTICAL seed centers from it; restarts then run sequentially on
    host-stepped iterations instead of one vmapped scan. NOT
    bitwise-comparable to the single-program path at >1 rank; the
    single-rank caller must route to :func:`kmeans` instead (the parity
    contract pinned in tests/test_shard.py).
    """
    import numpy as np

    if k < 1:
        raise ValueError(f"kmeans needs k >= 1, got {k}")
    x_local = jnp.asarray(x_local, jnp.float32)
    if x_local.ndim != 2 or x_local.shape[0] < 1:
        raise ValueError(
            f"kmeans_sharded needs a non-empty [N_local, d] matrix, got "
            f"shape {x_local.shape}")
    n_local = x_local.shape[0]
    take = min(n_local, SEED_SAMPLE_PER_RANK)
    idx = (np.arange(take, dtype=np.int64) * n_local) // take
    sample = jnp.asarray(gather("km_seed_sample",
                                np.asarray(x_local[np.unique(idx)])))
    keys = jax.random.split(key, n_init)
    best_inertia = None
    best_centers = None
    for i in range(n_init):
        centers = _kmeanspp_init(sample, k, keys[i])
        for _ in range(iters):
            counts, sums, _ = _local_lloyd_stats(x_local, centers, k)
            # One reduction per iteration: [k, d] sums and [k] counts ride
            # together so the transport cost is a single small message.
            packed = np.concatenate(
                [np.asarray(sums), np.asarray(counts)[:, None]], axis=1)
            packed = allreduce(f"km_stats/{i}", packed)
            g_sums, g_counts = packed[:, :-1], packed[:, -1]
            centers = jnp.where(
                jnp.asarray(g_counts)[:, None] > 0,
                jnp.asarray(g_sums) / jnp.maximum(
                    jnp.asarray(g_counts), 1.0)[:, None],
                centers)
        _, _, local_inertia = _local_lloyd_stats(x_local, centers, k)
        inertia = float(allreduce(
            f"km_inertia/{i}", np.asarray(local_inertia).reshape(1)))
        if best_inertia is None or inertia < best_inertia:
            best_inertia, best_centers = inertia, centers
    labels = jnp.argmin(_pairwise_sq_dists(x_local, best_centers),
                        axis=1).astype(jnp.int32)
    return labels, best_centers, best_inertia
