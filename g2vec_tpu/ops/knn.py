"""L5 — blocked exact top-k kernels for the interactive query plane.

Deliberately HOST-SIDE numpy, not a device kernel: the query plane
(serve/inventory.py) memory-maps float32 ``[G, H]`` embedding bundles
and promises O(block) resident bytes per query. A TPU kernel would need
the full table resident in HBM (pallas guide: HBM -> VMEM streaming
still requires the source array on-device), which is the copy the
inventory exists to avoid — and at query shapes (one ``[H]`` vector
against ``[G, H]``, H ~ 128) the work is a single gemv, far below
dispatch cost. The blocked loop keeps the touched working set to one
``block_rows x H`` slab at a time so a cold query against a memory-mapped
bundle faults in pages incrementally instead of materializing ``[G, H]``.

Exactness contract (pinned by tests/test_query.py): both kernels are
EXACT-equal — indices and values — to the naive full-sort numpy
reference. Blocking never changes a row's dot product (each row's
reduction is independent), ``argpartition`` + a full sort of the k
survivors reproduces the full stable sort's top-k, and ties break by
ascending index in both paths.
"""
from __future__ import annotations

import numpy as np


def row_norms(emb: np.ndarray, block_rows: int = 8192) -> np.ndarray:
    """Float32 L2 norm per row, computed in ``block_rows`` slabs.

    This is the ONE norm definition both bundle publication
    (io/writers.py) and query-time scoring use, so precomputed bundle
    norms and any recomputation agree bitwise.
    """
    g = emb.shape[0]
    out = np.empty(g, dtype=np.float32)
    for lo in range(0, g, block_rows):
        hi = min(g, lo + block_rows)
        block = np.asarray(emb[lo:hi], dtype=np.float32)
        out[lo:hi] = np.sqrt(np.einsum("ij,ij->i", block, block))
    return out


def _topk_desc(values: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k largest values, descending, ties by ascending
    index — via partial select (``argpartition``), never a full sort."""
    g = values.shape[0]
    k = min(k, g)
    if k <= 0:
        return np.empty(0, dtype=np.int64)
    if k < g:
        cand = np.argpartition(-values, k - 1)[:k]
        # argpartition picks an ARBITRARY element among values tied at
        # the k-boundary; the stable full sort picks the lowest index.
        # Re-derive the boundary cohort: everything strictly above the
        # threshold is in (< k of those exist), then tied rows fill the
        # remaining slots in ascending-index order (flatnonzero is
        # already ascending).
        thresh = values[cand].min()
        above = np.flatnonzero(values > thresh)
        ties = np.flatnonzero(values == thresh)
        cand = np.concatenate([above, ties[:k - above.size]])
    else:
        cand = np.arange(g)
    # Full sort only over the k survivors: primary key value desc,
    # secondary key index asc (lexsort's last key is primary).
    order = np.lexsort((cand, -values[cand]))
    return cand[order].astype(np.int64)


def cosine_topk(emb: np.ndarray, norms: np.ndarray, q: np.ndarray,
                k: int, exclude: int = -1,
                block_rows: int = 8192) -> "tuple[np.ndarray, np.ndarray]":
    """Exact cosine nearest neighbors of ``q`` among the rows of ``emb``.

    ``emb`` may be an ``np.memmap``; only one ``block_rows x H`` slab is
    materialized at a time (plus the ``[G]`` score vector). ``norms``
    are the precomputed :func:`row_norms`. Zero-norm rows (and a
    zero-norm query) score ``-2.0`` — strictly below every real cosine
    — instead of dividing by zero. ``exclude`` (the query gene itself)
    is scored out with ``-inf``. Returns ``(idx, sims)`` with the k
    best rows, similarity descending, ties by ascending index.
    """
    g, h = emb.shape
    q = np.asarray(q, dtype=np.float32).reshape(h)
    qn = np.sqrt(np.dot(q, q))
    sims = np.empty(g, dtype=np.float32)
    for lo in range(0, g, block_rows):
        hi = min(g, lo + block_rows)
        block = np.asarray(emb[lo:hi], dtype=np.float32)
        sims[lo:hi] = block @ q
    denom = norms * qn
    ok = denom > 0
    sims = np.where(ok, sims / np.where(ok, denom, 1), np.float32(-2.0))
    if 0 <= exclude < g:
        sims[exclude] = -np.inf
    idx = _topk_desc(sims, k)
    return idx, sims[idx]


def cosine_topk_subset(emb: np.ndarray, norms: np.ndarray,
                       rows: np.ndarray, q: np.ndarray, k: int,
                       exclude: int = -1, block_rows: int = 8192
                       ) -> "tuple[np.ndarray, np.ndarray]":
    """:func:`cosine_topk` restricted to a candidate subset of rows.

    ``rows`` MUST be sorted ascending and duplicate-free (the IVF
    probe in ops/ann.py produces exactly that); sortedness is what
    makes tie-breaking identical to the full kernel — position order
    within the candidate score vector IS ascending global row id, so
    ``_topk_desc``'s ascending-position tie rule resolves ties by
    ascending global index, same as the exact path.

    Float-exactness contract (pinned by tests/test_ann.py): each
    candidate row's score is computed with the SAME arithmetic as
    :func:`cosine_topk` — one row dot ``emb[r] @ q``, the same
    ``np.where`` zero-norm guard, the same ``-inf`` exclude — and a
    row's dot product does not depend on which other rows share its
    block. So whenever the true top-k rows are all in ``rows``, the
    returned (idx, sims) equal the exact kernel's bitwise.
    """
    g, h = emb.shape
    rows = np.asarray(rows, dtype=np.int64).reshape(-1)
    m = rows.shape[0]
    q = np.asarray(q, dtype=np.float32).reshape(h)
    qn = np.sqrt(np.dot(q, q))
    sims = np.empty(m, dtype=np.float32)
    for lo in range(0, m, block_rows):
        hi = min(m, lo + block_rows)
        # Fancy-indexed gather materializes one candidate slab at a
        # time; a memory-mapped ``emb`` faults only the touched pages.
        block = np.asarray(emb[rows[lo:hi]], dtype=np.float32)
        sims[lo:hi] = block @ q
    denom = np.asarray(norms, dtype=np.float32)[rows] * qn
    ok = denom > 0
    sims = np.where(ok, sims / np.where(ok, denom, 1), np.float32(-2.0))
    if 0 <= exclude < g:
        pos = np.searchsorted(rows, exclude)
        if pos < m and rows[pos] == exclude:
            sims[pos] = -np.inf
    loc = _topk_desc(sims, k)
    return rows[loc], sims[loc]


def topk_scores(scores: np.ndarray, k: int
                ) -> "tuple[np.ndarray, np.ndarray]":
    """Top-k indices of a 1-D score vector by partial select.

    The biomarker sub-op's kernel: one row of the bundle's ``[2, G]``
    prognostic score matrix in, ``(idx, scores[idx])`` out — score
    descending, ties by ascending index, exact-equal to the full stable
    sort.
    """
    scores = np.asarray(scores, dtype=np.float32).reshape(-1)
    idx = _topk_desc(scores, k)
    return idx, scores[idx]
