"""CSR-native device walk sampler, bit-exact with the host C++ walker.

This module replaces the legacy dense-adjacency device walker as the
production device sampler (docstring lineage: ``ops/walker.py`` began as
the faithful JAX port of generate_randomPath, ref: G2Vec.py:328-346 —
weighted no-revisit walks, Categorical over the current node's positive
out-edge weights restricted to unvisited targets, early stop at dead
ends — but its dense form materializes the [G, G] transition matrix and
its sparse form draws from a jax.random PRNG family the host sampler
cannot reproduce). Here the walk runs over the SAME CSR arrays the
native sampler scans (ops/host_walker.edges_to_csr) and draws from the
SAME PRNG: splitmix64, emulated on device as uint32 lane pairs with one
fixed-constant state advance per uniform draw — the exact per-draw
contract ``WalkStateBatch.rng`` pins (PR 13). Device paths are therefore
**bitwise identical** to native/walker.cpp for the same (CSR bytes, walk
params, seed): every golden, walk-cache entry, and statistical band
transfers between backends unchanged.

How bit-exactness is achieved (the parity contract, ARCHITECTURE.md
§24):

- splitmix64 state and outputs are uint64 values carried as (hi, lo)
  uint32 lane pairs; add/xor/shift/multiply are emulated lanewise
  (16-bit limb products for the low-64 multiply), so every stream word
  equals the C++ ``uint64_t`` stream word.
- uniform01 is ``(out >> 11) * 2^-53`` in the C++ walker; on device the
  53-bit integer splits as hi-21/lo-32 words and
  ``u = hi*2^-21 + lo*2^-53`` — both scalings are exact powers of two
  and the sum is exactly representable, so ``u`` is the identical f64.
- the per-step CDF is accumulated in float64 by an explicit SEQUENTIAL
  scan over the degree axis (XLA's ``cumsum`` uses a pairwise tree and
  does NOT reproduce left-to-right accumulation); ineligible slots add
  exactly 0.0, so masked lane sums equal the host's compacted cumbuf.
- selection counts eligible slots with ``cum <= target`` — the same
  index the host's lower-bound search returns — with the same
  last-eligible fallback when rounding puts ``target`` at ``total``.
- the state advances ONLY on an actual draw: dead ends and suspensions
  break before drawing, exactly as walk_range/walk_partial_range do.

float64 on device: CPU and GPU backends execute IEEE f64 natively (the
tier-1 parity pins run on CPU). TPU chips have no native f64 — XLA:TPU
emulation is not IEEE-bitwise — so on TPU this sampler is
throughput-correct but the bitwise contract is only *claimed* where a
chip-gated bench line has re-checked it (BENCH_DEVICE_WALK.json keeps
those lines gated, never faked).

Suspend/resume: :func:`advance_walk_states_device` consumes the same
:class:`~g2vec_tpu.ops.host_walker.WalkStateBatch` the native
walk_partial advances — (gene, remaining, rng-word) state round-trips
between backends mid-walk with word-for-word rng parity.
"""
from __future__ import annotations

import functools
from typing import Optional, Set, Tuple

import numpy as np

from g2vec_tpu.ops.host_walker import (ShardPlan, WalkStateBatch,
                                       edges_to_csr)

# The splitmix64 constants (Steele et al.; native/walker.cpp uses the
# same literals).
GOLDEN = 0x9E3779B97F4A7C15
MIX1 = 0xBF58476D1CE4E5B9
MIX2 = 0x94D049BB133111EB
_MASK64 = (1 << 64) - 1


# ---- host-side reference + seeding (the fuzz battery's oracle) -------------

def splitmix64_ref(state: int) -> Tuple[int, int]:
    """One splitmix64 draw in pure Python: (new_state, output word).

    The word-for-word oracle the device emulation is fuzzed against —
    matches native/walker.cpp's ``splitmix64(uint64_t&)`` exactly.
    """
    state = (state + GOLDEN) & _MASK64
    z = state
    z = ((z ^ (z >> 30)) * MIX1) & _MASK64
    z = ((z ^ (z >> 27)) * MIX2) & _MASK64
    return state, z ^ (z >> 31)


def uniform01_ref(state: int) -> Tuple[int, float]:
    """One uniform01 draw in pure Python: (new_state, u in [0, 1))."""
    state, z = splitmix64_ref(state)
    return state, float(z >> 11) * (2.0 ** -53)


def init_walk_state_np(seed: int, stream_ids: np.ndarray) -> np.ndarray:
    """The per-walker PRNG init, in numpy: raw state =
    ``seed ^ (stream_id * GOLDEN)`` plus one discarded splitmix64 call
    (state advance only — the discarded output never touches state).
    Bit-identical to native ``g2v_init_walk_state`` without needing the
    C++ toolchain, so a toolchain-free host can still seed device walks.
    """
    sid = np.ascontiguousarray(stream_ids, dtype=np.uint64)
    seed64 = np.uint64(seed & _MASK64)
    with np.errstate(over="ignore"):
        st = (seed64 ^ (sid * np.uint64(GOLDEN))) + np.uint64(GOLDEN)
    return st


# ---- uint32 lane-pair u64 emulation (device) -------------------------------
# Everything below runs under jit; uint32 arithmetic wraps mod 2^32 on
# every backend, which is exactly the carry discipline the emulation
# needs. Python int scalars stay weakly typed, so `x >> 11` keeps x's
# uint32 dtype.

def _u64_add(xh, xl, yh, yl):
    lo = xl + yl
    carry = (lo < xl).astype(lo.dtype)
    return xh + yh + carry, lo


def _mul32_wide(a, b):
    """uint32 x uint32 -> (hi, lo) uint32 pair of the 64-bit product,
    via 16-bit limbs (no 64-bit multiplier needed on any backend)."""
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00 = a0 * b0
    mid = a0 * b1 + a1 * b0          # may wrap: the wrap IS bit 2^48
    mid_wrap = (mid < a0 * b1).astype(a.dtype)
    lo = p00 + (mid << 16)
    carry = (lo < p00).astype(a.dtype)
    hi = a1 * b1 + (mid >> 16) + (mid_wrap << 16) + carry
    return hi, lo


def _u64_mul(xh, xl, yh, yl):
    """Low 64 bits of the u64 product (all splitmix64 needs)."""
    hi, lo = _mul32_wide(xl, yl)
    return hi + xl * yh + xh * yl, lo


def _u64_xorshr(h, l, k: int):
    """(h, l) ^= (h, l) >> k for 0 < k < 32."""
    return h ^ (h >> k), l ^ ((l >> k) | (h << (32 - k)))


def _splitmix64_device(sh, sl):
    """One device splitmix64 draw on (hi, lo) uint32 lane pairs:
    returns (new_state_hi, new_state_lo, out_hi, out_lo)."""
    import jax.numpy as jnp

    sh, sl = _u64_add(sh, sl, jnp.uint32(GOLDEN >> 32),
                      jnp.uint32(GOLDEN & 0xFFFFFFFF))
    zh, zl = _u64_xorshr(sh, sl, 30)
    zh, zl = _u64_mul(zh, zl, jnp.uint32(MIX1 >> 32),
                      jnp.uint32(MIX1 & 0xFFFFFFFF))
    zh, zl = _u64_xorshr(zh, zl, 27)
    zh, zl = _u64_mul(zh, zl, jnp.uint32(MIX2 >> 32),
                      jnp.uint32(MIX2 & 0xFFFFFFFF))
    zh, zl = _u64_xorshr(zh, zl, 31)
    return sh, sl, zh, zl


def _uniform01_device(zh, zl):
    """``(word >> 11) * 2^-53`` from the (hi, lo) output pair, exactly:
    the 53-bit integer splits as 21 high / 32 low bits, each converts to
    f64 exactly, each scaling is a power of two, and the sum is exactly
    representable — IEEE addition then returns it exactly."""
    import jax.numpy as jnp

    v_hi = (zh >> 11).astype(jnp.float64)
    v_lo = ((zh << 21) | (zl >> 11)).astype(jnp.float64)
    return v_hi * (2.0 ** -21) + v_lo * (2.0 ** -53)


# ---- the walk kernel -------------------------------------------------------

def _x64():
    """float64 lives behind jax's x64 switch; the kernels trace AND run
    inside this context so the f64 CDF math is real f64 everywhere."""
    from jax.experimental import enable_x64

    return enable_x64()


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


@functools.lru_cache(maxsize=32)
def _get_walk_fn(len_path: int, d_slots: int):
    """The jitted step scan for (len_path, padded-degree) — walker count
    and CSR sizes specialize through jit's own shape cache."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    D, L = d_slots, len_path

    def run(indptr, indices_pad, weights_pad, avail, cur, rng_hi, rng_lo,
            pos, paths):
        n_walkers = cur.shape[0]
        d_arange = jnp.arange(D, dtype=jnp.int32)
        l_arange = jnp.arange(L, dtype=jnp.int32)
        susp0 = jnp.zeros((n_walkers,), dtype=bool)

        def step(carry, _):
            cur, sh, sl, pos, paths, susp, dead = carry
            # Gate order = walk_partial_range's: the length check guards
            # the availability check guards the scan guards the draw.
            not_full = pos < L
            live = (~susp) & (~dead) & not_full
            avail_cur = avail[cur] != 0
            suspend_now = live & (~avail_cur)
            susp = susp | suspend_now
            active = live & avail_cur
            # CSR row slice at a static width: indices/weights carry D
            # trailing pad entries, so the dynamic_slice never clamps.
            row_off = indptr[cur]
            deg = indptr[cur + 1] - row_off
            cand = jax.vmap(
                lambda o: lax.dynamic_slice(indices_pad, (o,), (D,)))(row_off)
            wrow = jax.vmap(
                lambda o: lax.dynamic_slice(weights_pad, (o,), (D,)))(row_off)
            in_row = d_arange[None, :] < deg[:, None]
            # No-revisit via path replay (the C++ visited mask is wiped
            # by path replay too): -1 pads never match a candidate, and
            # out-of-row pad candidates are masked by in_row.
            seen = (paths[:, :, None] == cand[:, None, :]).any(axis=1)
            elig = in_row & (~seen) & (wrow > 0.0)
            # Sequential f64 mass accumulation over the degree axis —
            # jnp.cumsum's pairwise tree would NOT reproduce the host's
            # left-to-right double sums; ineligible lanes add exactly
            # 0.0, so eligible lanes hold exactly the compacted cumbuf.
            wm = jnp.where(elig, wrow.astype(jnp.float64), 0.0)

            def cum_step(acc, col):
                acc = acc + col
                return acc, acc

            total, cum_t = lax.scan(
                cum_step, jnp.zeros((n_walkers,), dtype=jnp.float64), wm.T)
            cum = cum_t.T
            m = jnp.sum(elig, axis=1, dtype=jnp.int32)
            dead_now = active & ((m == 0) | (total <= 0.0))
            draw = active & (~dead_now)
            # One state advance per ACTUAL draw: advance speculatively,
            # commit only where a draw happens (dead ends/suspensions
            # freeze the stream, exactly as the C++ break does).
            nsh, nsl, zh, zl = _splitmix64_device(sh, sl)
            u = _uniform01_device(zh, zl)
            sh = jnp.where(draw, nsh, sh)
            sl = jnp.where(draw, nsl, sl)
            target = u * total
            # The host's lower-bound: smallest eligible j with
            # target < cum[j] == the count of eligible cum <= target;
            # rounding can put target at total — fall through to the
            # last eligible slot, as the C++ clamp does.
            j = jnp.sum(elig & (cum <= target[:, None]), axis=1,
                        dtype=jnp.int32)
            j = jnp.minimum(j, jnp.maximum(m - 1, 0))
            rank = jnp.cumsum(elig.astype(jnp.int32), axis=1) - 1
            sel = elig & (rank == j[:, None])
            nxt = jnp.sum(jnp.where(sel, cand, 0), axis=1,
                          dtype=jnp.int32)
            write = draw[:, None] & (l_arange[None, :] == pos[:, None])
            paths = jnp.where(write, nxt[:, None], paths)
            pos = pos + draw.astype(jnp.int32)
            cur = jnp.where(draw, nxt, cur)
            return (cur, sh, sl, pos, paths, susp, dead | dead_now), None

        carry = (cur, rng_hi, rng_lo, pos, paths, susp0, susp0)
        # L-1 trips cover the worst case: a pos=1 resume draws L-2 steps
        # and still needs one trip to notice a terminal suspension.
        (cur, rng_hi, rng_lo, pos, paths, susp, _), _ = lax.scan(
            step, carry, None, length=L - 1)
        return cur, rng_hi, rng_lo, pos, paths, susp

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _get_pack_fn(nbytes: int):
    """Jitted path -> np.packbits-layout packer: an O(W*L) bit scatter
    (no [W, G] dense transient; no-revisit means every (byte, bit)
    contribution is unique, so uint8 add IS bitwise or). Column
    ``nbytes`` is the dump slot for -1 pads, sliced off."""
    import jax
    import jax.numpy as jnp

    def pack(paths):
        n, length = paths.shape
        valid = paths >= 0
        node = jnp.where(valid, paths, 0)
        byte_idx = jnp.where(valid, node >> 3, nbytes)
        bits = jnp.where(valid, (128 >> (node & 7)), 0).astype(jnp.uint8)
        rows = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32)[:, None], (n, length))
        out = jnp.zeros((n, nbytes + 1), dtype=jnp.uint8)
        out = out.at[rows, byte_idx].add(bits)
        return out[:, :nbytes]

    return jax.jit(pack)


def _padded_csr(csr, d_slots: int):
    """CSR arrays with ``d_slots`` trailing pad entries so the static-
    width row slice never clamps (pad weights are 0 => never eligible)."""
    indptr, indices, weights = csr
    indptr = np.ascontiguousarray(indptr, dtype=np.int32)
    indices = np.concatenate(
        [np.ascontiguousarray(indices, dtype=np.int32),
         np.zeros(d_slots, np.int32)])
    weights = np.concatenate(
        [np.ascontiguousarray(weights, dtype=np.float32),
         np.zeros(d_slots, np.float32)])
    return indptr, indices, weights


def _max_degree(indptr: np.ndarray) -> int:
    if indptr.shape[0] <= 1:
        return 1
    return max(1, int(np.max(indptr[1:] - indptr[:-1])))


def _split_rng(rng: np.ndarray):
    rng = np.ascontiguousarray(rng, dtype=np.uint64)
    return ((rng >> np.uint64(32)).astype(np.uint32),
            (rng & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _join_rng(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return ((hi.astype(np.uint64) << np.uint64(32))
            | lo.astype(np.uint64))


def _run_states(csr, n_genes: int, avail: np.ndarray, cur: np.ndarray,
                rng: np.ndarray, pos: np.ndarray, paths: np.ndarray,
                len_path: int, *, as_device: bool = False):
    """Advance explicit walk states on device; returns
    (cur, rng, pos, paths, status) — numpy, or device arrays for paths
    when ``as_device`` (the fused-feed fast path keeps them resident)."""
    if len_path < 1:
        raise ValueError(f"len_path must be >= 1, got {len_path}")
    indptr = np.ascontiguousarray(csr[0], dtype=np.int32)
    d_slots = _pow2(_max_degree(indptr))
    indptr, indices_pad, weights_pad = _padded_csr(
        (indptr, csr[1], csr[2]), d_slots)
    avail = np.ascontiguousarray(avail, dtype=np.uint8)
    n = cur.shape[0]
    # Pad the walker axis to a power of two: shard tails reuse the
    # bucket's compiled program instead of re-tracing per remainder
    # width. Pad walkers are born full (pos = len_path) — inert.
    n_pad = _pow2(max(1, n))
    if n_pad != n:
        pad = n_pad - n
        cur = np.concatenate([cur, np.zeros(pad, np.int32)])
        rng = np.concatenate([rng, np.zeros(pad, np.uint64)])
        pos = np.concatenate(
            [pos, np.full(pad, len_path, np.int32)])
        paths = np.concatenate(
            [paths, np.full((pad, len_path), -1, np.int32)], axis=0)
    rng_hi, rng_lo = _split_rng(rng)
    with _x64():
        fn = _get_walk_fn(len_path, d_slots)
        out = fn(indptr, indices_pad, weights_pad, avail,
                 np.ascontiguousarray(cur, dtype=np.int32), rng_hi, rng_lo,
                 np.ascontiguousarray(pos, dtype=np.int32),
                 np.ascontiguousarray(paths, dtype=np.int32))
        if as_device:
            cur2, hi, lo, pos2, paths2, susp = out
            return (np.asarray(cur2)[:n],
                    _join_rng(np.asarray(hi)[:n], np.asarray(lo)[:n]),
                    np.asarray(pos2)[:n], paths2, susp, n)
        cur2, hi, lo, pos2, paths2, susp = [np.asarray(a) for a in out]
    return (cur2[:n], _join_rng(hi[:n], lo[:n]), pos2[:n], paths2[:n],
            susp[:n].astype(np.uint8))


def advance_walk_states_device(states: WalkStateBatch, csr, n_genes: int,
                               avail: np.ndarray, len_path: int,
                               n_threads: int = 0) -> np.ndarray:
    """Device twin of :func:`~g2vec_tpu.ops.host_walker.
    advance_walk_states`: advance every walk IN PLACE over an
    availability-masked CSR until it finishes or suspends; returns the
    [M] uint8 status array (0 finished, 1 suspended). Bit-identical to
    the native advance for the same states — including the frozen rng
    word of a suspended walker (``n_threads`` is accepted for signature
    parity and ignored; the device batches instead of threading)."""
    cur, rng, pos, paths, status = _run_states(
        csr, n_genes, avail, states.cur, states.rng, states.pos,
        states.paths, len_path)
    states.cur[:] = cur
    states.rng[:] = rng
    states.pos[:] = pos
    states.paths[:] = paths
    return status


def _shard_init(plan: ShardPlan, shard: int, seed: int,
                starts: Optional[np.ndarray]):
    """Initial (cur, rng, pos, paths) for a shard — walk_shard's
    rep-major walker order and global-index PRNG streams, seeded by the
    numpy init (no native lib needed)."""
    lo, hi = plan.start_range(shard)
    k = hi - lo
    sub = (np.arange(lo, hi, dtype=np.int32) if starts is None
           else np.ascontiguousarray(starts[lo:hi], dtype=np.int32))
    start_col = np.tile(sub, plan.reps)
    wids = (np.arange(plan.reps, dtype=np.uint64)[:, None]
            * np.uint64(plan.n_starts)
            + np.arange(lo, hi, dtype=np.uint64)[None, :]).ravel()
    n = k * plan.reps
    paths = np.full((n, plan.len_path), -1, np.int32)
    paths[:, 0] = start_col
    return (np.ascontiguousarray(start_col), init_walk_state_np(seed, wids),
            np.ones(n, np.int32), paths)


def walk_shard_device_arrays(src, dst, w, n_genes: int, plan: ShardPlan,
                             shard: int, *, seed: int,
                             csr: Optional[tuple] = None,
                             starts: Optional[np.ndarray] = None):
    """One group's shard rows sampled on device ->
    ``(packed_device [rows, ceil(G/8)] uint8, rows)`` with the packed
    array still DEVICE-RESIDENT (the fused streaming feed slices it into
    the minibatch step without a host round-trip). Byte-identical to
    :func:`~g2vec_tpu.ops.host_walker.walk_shard` for the same (plan,
    shard, seed, CSR bytes)."""
    from g2vec_tpu.resilience.faults import fault_point

    if starts is not None and len(starts) != plan.n_starts:
        raise ValueError(
            f"plan.n_starts ({plan.n_starts}) must match len(starts) "
            f"({len(starts)})")
    if csr is None:
        csr = edges_to_csr(np.asarray(src), np.asarray(dst), np.asarray(w),
                           n_genes)
    # The mid-scan fault seam: an injected crash lands between state
    # init and the device scan — recovery is a clean recompute (the
    # sampler is a pure function of (plan, shard, seed)), and the drill
    # pins that the recomputed rows are byte-identical.
    fault_point("device_walk", epoch=shard)
    cur, rng, pos, paths = _shard_init(plan, shard, seed, starts)
    avail = np.ones(n_genes, np.uint8)
    _, _, _, paths_dev, _, n = _run_states(
        csr, n_genes, avail, cur, rng, pos, paths, plan.len_path,
        as_device=True)
    nbytes = (n_genes + 7) // 8
    with _x64():
        packed = _get_pack_fn(nbytes)(paths_dev)[:n]
    return packed, n


def walk_shard_device(src, dst, w, n_genes: int, plan: ShardPlan,
                      shard: int, *, seed: int, n_threads: int = 0,
                      csr: Optional[tuple] = None,
                      starts: Optional[np.ndarray] = None) -> np.ndarray:
    """Drop-in device twin of :func:`~g2vec_tpu.ops.host_walker.
    walk_shard` — same signature (``n_threads`` ignored), same
    [group_rows, ceil(G/8)] packed rows, byte-for-byte."""
    packed, _ = walk_shard_device_arrays(
        src, dst, w, n_genes, plan, shard, seed=seed, csr=csr,
        starts=starts)
    return np.asarray(packed)


def walk_packed_rows_device(src, dst, w, n_genes: int, *, len_path: int,
                            reps: int, seed: int,
                            starts: Optional[np.ndarray] = None,
                            walker_lo: int = 0,
                            walker_hi: Optional[int] = None,
                            csr: Optional[tuple] = None) -> np.ndarray:
    """Device twin of :func:`~g2vec_tpu.ops.host_walker.
    walk_packed_rows`: walks for the global walker index range
    [walker_lo, walker_hi) -> packed multi-hot rows, byte-identical to
    the native sampler's."""
    if len_path < 1:
        raise ValueError(f"len_path must be >= 1, got {len_path}")
    if starts is None:
        starts = np.arange(n_genes, dtype=np.int32)
    starts = np.asarray(starts, dtype=np.int32)
    if starts.size and (starts.min() < 0 or starts.max() >= n_genes):
        raise ValueError(f"starts contains node ids outside [0, {n_genes})")
    n_starts = starts.shape[0]
    total = n_starts * reps
    walker_hi = total if walker_hi is None else walker_hi
    if not (0 <= walker_lo <= walker_hi <= total):
        raise ValueError(
            f"walker range [{walker_lo}, {walker_hi}) outside [0, {total}]")
    if csr is None:
        csr = edges_to_csr(np.asarray(src), np.asarray(dst), np.asarray(w),
                           n_genes)
    all_starts = np.tile(starts, reps)[walker_lo:walker_hi]
    wids = np.arange(walker_lo, walker_hi, dtype=np.uint64)
    n = walker_hi - walker_lo
    paths = np.full((n, len_path), -1, np.int32)
    paths[:, 0] = all_starts
    avail = np.ones(n_genes, np.uint8)
    _, _, _, paths_dev, _, n_live = _run_states(
        csr, n_genes, avail, np.ascontiguousarray(all_starts),
        init_walk_state_np(seed, wids), np.ones(n, np.int32), paths,
        len_path, as_device=True)
    nbytes = (n_genes + 7) // 8
    with _x64():
        packed = _get_pack_fn(nbytes)(paths_dev)[:n_live]
    return np.asarray(packed)


def generate_path_set_device(src, dst, w, n_genes: int, *, len_path: int,
                             reps: int, seed: int,
                             starts: Optional[np.ndarray] = None) -> \
        Set[bytes]:
    """All-sources x reps device walks -> set of packed multi-hot rows.

    The device twin of :func:`~g2vec_tpu.ops.host_walker.
    generate_path_set_native` — byte-identical rows, so the two backends
    share one walk-cache PRNG family (g2vec_tpu/cache.py NATIVE_FAMILY)
    and a device run HITS a host-populated cache entry.
    """
    packed = walk_packed_rows_device(
        src, dst, w, n_genes, len_path=len_path, reps=reps, seed=seed,
        starts=starts)
    return {row.tobytes() for row in packed}
