"""Pallas TPU kernel: matmul over a bit-packed multi-hot matrix.

The trainer's hot op is ``X @ W_ih`` where X is a 0/1 multi-hot path matrix
(ref: the CBOW input, G2Vec.py:238-239). Storing X densely in bf16 costs
~550 MB of HBM at example scale and every epoch re-reads it three times
(train fwd, dW, val eval — the train eval rides the next grad forward
after trainer.py's eval-train fold; the reference re-read it a fourth
time). This kernel keeps X **bit-packed**
(uint8, 8 genes/byte — 16x smaller) in HBM and unpacks tiles on the fly in
VMEM, fused into the MXU matmul, so the HBM traffic for X drops 16x. The
packed-vs-XLA-dense speedup at the trainer's exact fwd shape is a MEASURED
bench metric, not a docstring number: ``packed_matmul_vs_xla_dense`` in the
driver's BENCH_r{N}.json (bench.py stage 3; interactive spot checks on a
v5e chip saw ~0.34 ms vs ~2.7 ms at 36864 x 8192 x 128).

Layout: genes are packed **blockwise** (`pack_blockwise`): within each
``LANE_BLOCK``-gene block, gene offset ``j = c + k*(LANE_BLOCK//8)`` lives in
bit ``k`` (MSB-first) of byte ``c``. This is exactly the layout produced by
``pltpu.repeat(bytes, 8, axis=1)`` (tile-style repeat) followed by a
per-column shift — the unpack is three VPU ops per element with the shift
array hoisted out of the chunk loop (the hoist alone is worth 5x; Mosaic
does not CSE the iota across `lax.fori_loop` iterations).

Both directions are provided and glued with ``jax.custom_vjp``, each a 2-D
grid over (row tiles x gene blocks) so NO whole-matrix VMEM resident caps
the problem size (round-1 verdict: the old whole-[G,H] bwd accumulator
excluded hidden=1024 at any realistic G):
  - forward  ``unpack(P) @ W``    — grid (rows, gene blocks), gene blocks
    innermost; the [ROW_BLOCK, H] output tile stays VMEM-resident across a
    row's gene blocks (its index map is constant there) and accumulates;
  - backward ``unpack(P).T @ G``  — grid (gene blocks, rows), rows
    innermost; the [gene_block, H] dW tile stays resident across row steps.

The gene block and the row tile adapt to H via a whole-working-set VMEM
model (``_vmem_step_bytes``: resident tile + double-buffered streamed tiles
+ unpack temporaries), so G can grow without bound and H up to 1024 — the
shapes of every BASELINE config, where the old whole-table kernel stopped
at G*H*4 <= 8 MB.

Use ``packed_matmul_available()`` to gate: it requires a TPU backend (or
``interpret=True`` for CPU tests), lane-aligned shapes, and a minimum grid
step within the VMEM budget.
"""
from __future__ import annotations

import functools
import json
import os
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Gene-axis block: the unit of the blockwise bit layout and of the in-kernel
# chunk loop. 1024 genes -> 128 byte lanes, exactly one lane tile.
LANE_BLOCK = 1024
_LB_BYTES = LANE_BLOCK // 8
# Row padding quantum (callers pad row counts to this); the kernels
# themselves may run a SMALLER row tile when H is large (_row_block) — 512
# is a multiple of every effective tile, so padded inputs stay aligned.
ROW_BLOCK = 512

# Whole-working-set VMEM budget per grid step: resident tile + streamed
# (double-buffered) tiles + unpack temporaries, against the ~16 MB/core of
# v4/v5e with slack for Mosaic's own spills.
_VMEM_STEP_BUDGET = 14 * 1024 * 1024


def _row_block(h: int) -> int:
    """Effective row tile: streamed-tile VMEM scales with rows*H, so rows
    shrink as H grows (512 stays the outer padding quantum)."""
    if h <= 256:
        return 512
    return 256


def _vmem_step_bytes(gb: int, h: int, rb: int) -> int:
    """Worst-direction VMEM working set of one grid step (bytes).

    Counts, per the kernel bodies below: the resident f32 tile (fwd output /
    bwd dW), double-buffered streamed tiles (W bf16 in fwd; g_out bf16 in
    bwd — _pm_bwd casts the cotangent BEFORE the call, so the kernel's
    astype is a no-op), double-buffered packed tiles, the per-slab dot
    output (bwd), the separate f32 acc (fwd), and the unpack temporaries
    (rep int32 + hoisted shift int32 + x bf16 = 10 bytes/element)."""
    unpack = rb * LANE_BLOCK * 10
    p_tiles = 2 * rb * (gb // 8)
    bwd = (gb * h * 4 + 2 * rb * h * 2
           + LANE_BLOCK * h * 4 + p_tiles + unpack)
    fwd = 2 * gb * h * 2 + 2 * rb * h * 4 + p_tiles + unpack
    return max(bwd, fwd)


def pack_blockwise(x: np.ndarray, block: int = LANE_BLOCK) -> np.ndarray:
    """[M, G] 0/1 -> [M, G//8] uint8 in the kernel's blockwise bit layout.

    Within each ``block``-gene slab: gene offset ``j = c + k*(block//8)``
    is bit ``k`` (MSB-first) of byte ``c``. G must be a multiple of block.
    """
    m, g = x.shape
    if g % block:
        raise ValueError(f"n_genes {g} not a multiple of pack block {block}")
    bb = block // 8
    xr = np.ascontiguousarray(
        x.reshape(m, g // block, 8, bb).transpose(0, 1, 3, 2))
    return np.packbits(xr.astype(bool), axis=3, bitorder="big").reshape(m, g // 8)


def unpack_blockwise(packed: np.ndarray, block: int = LANE_BLOCK) -> np.ndarray:
    """Host-side inverse of :func:`pack_blockwise` (tests, checkpoints)."""
    m, nb = packed.shape
    g = nb * 8
    bb = block // 8
    bits = np.unpackbits(packed.reshape(m, g // block, bb, 1), axis=3,
                         bitorder="big")
    return bits.transpose(0, 1, 3, 2).reshape(m, g)


def _shift_array(rows: int) -> jax.Array:
    """[rows, LANE_BLOCK] int32: MSB-first shift for each unpacked column."""
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, LANE_BLOCK), 1)
    return 7 - col // _LB_BYTES


def _unpack_tile(p_chunk: jax.Array, shift: jax.Array,
                 interpret: bool = False) -> jax.Array:
    """[rows, LB_BYTES] uint8 -> [rows, LANE_BLOCK] bf16 0/1.

    ``interpret`` must match the enclosing pallas_call's flag: Mosaic's
    ``pltpu.repeat`` is a TILE repeat (concatenate whole copies along the
    lane axis — the layout pack_blockwise encodes), but the pallas
    interpreter in this jax version executes it as an ELEMENT repeat
    (``jnp.repeat`` semantics), silently scrambling the bit<->gene map on
    CPU. The interpret path therefore spells the tile repeat out as an
    explicit concatenate — identical math, and the interpret-mode tests
    exercise the real layout again.
    """
    p32 = p_chunk.astype(jnp.int32)
    if interpret:
        rep = jnp.concatenate([p32] * 8, axis=1)
    else:
        rep = pltpu.repeat(p32, 8, axis=1)
    return ((rep >> shift) & 1).astype(jnp.bfloat16)


def _blocks_per_group(g: int, h: int) -> int:
    """LANE_BLOCK slabs per gene block: as many as keep the whole per-step
    working set within budget, while dividing G's slab count evenly (the
    grid floor-divides; an uneven tail would be dropped)."""
    n_blocks = g // LANE_BLOCK
    rb = _row_block(h)
    cap = 1
    while (cap < n_blocks
           and _vmem_step_bytes((cap + 1) * LANE_BLOCK, h, rb)
           <= _VMEM_STEP_BUDGET):
        cap += 1
    bpg = min(n_blocks, cap)
    while n_blocks % bpg:
        bpg -= 1
    return bpg


def _fwd_kernel(p_ref, w_ref, o_ref, *, interpret: bool = False):
    nchunks = w_ref.shape[0] // LANE_BLOCK
    shift = _shift_array(p_ref.shape[0])

    # Gene blocks are the INNER grid dim: the output tile's index map is
    # constant across them, so it stays VMEM-resident and accumulates.
    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(c, acc):
        x = _unpack_tile(p_ref[:, pl.ds(c * _LB_BYTES, _LB_BYTES)], shift,
                         interpret)
        wc = w_ref[pl.ds(c * LANE_BLOCK, LANE_BLOCK), :]
        return acc + jax.lax.dot_general(
            x, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jnp.zeros((p_ref.shape[0], w_ref.shape[1]), jnp.float32)
    o_ref[:] += jax.lax.fori_loop(0, nchunks, body, acc)


def _bwd_kernel(p_ref, g_ref, o_ref, *, interpret: bool = False):
    nchunks = o_ref.shape[0] // LANE_BLOCK
    shift = _shift_array(p_ref.shape[0])

    # Row tiles are the INNER grid dim here: the [gene_block, H] dW tile
    # stays resident across a gene block's row sweep.
    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    gtile = g_ref[:].astype(jnp.bfloat16)

    def body(c, _):
        x = _unpack_tile(p_ref[:, pl.ds(c * _LB_BYTES, _LB_BYTES)], shift,
                         interpret)
        sl = pl.ds(c * LANE_BLOCK, LANE_BLOCK)
        o_ref[sl, :] += jax.lax.dot_general(
            x, gtile, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return 0

    jax.lax.fori_loop(0, nchunks, body, 0)


# ---------------------------------------------------------------------------
# Tile planning: heuristic defaults + measured (autotuned) overrides.
# ---------------------------------------------------------------------------

#: Measured tile overrides, installed by :func:`autotune_packed_matmul` (or
#: :func:`load_tuned` from the persistent --cache-dir tier). Keyed by the
#: exact problem (m, g, h); values per direction: (row_block,
#: blocks_per_group). The heuristic (_row_block/_blocks_per_group) stays the
#: fallback for any shape not measured.
_TUNED: Dict[Tuple[int, int, int], Dict[str, Tuple[int, int]]] = {}

#: Monotonic token bumped on every override install: callers that cache
#: compiled programs embedding a tile plan (the trainer's chunk-fn LRU) key
#: on this so a re-tune invalidates them instead of silently running stale
#: tiles.
_TUNED_VERSION = 0

#: Bump on ANY change to the kernel bodies, the VMEM model, or the
#: candidate space — persisted measurements from an older kernel must
#: re-tune, not load.
AUTOTUNE_SCHEMA = 1


def tuned_token() -> int:
    """Current override-install counter (cache-key ingredient)."""
    return _TUNED_VERSION


#: Backend signature each in-memory entry was measured under: an
#: interpret-mode plan must not satisfy a TPU run of the same shape.
_TUNED_BACKEND: Dict[Tuple[int, int, int], str] = {}


def _install_tuned(m: int, g: int, h: int,
                   plans: Dict[str, Tuple[int, int]],
                   backend_tag: str = "") -> None:
    global _TUNED_VERSION
    _TUNED[(m, g, h)] = {d: (int(rb), int(bpg))
                         for d, (rb, bpg) in plans.items()}
    _TUNED_BACKEND[(m, g, h)] = backend_tag
    _TUNED_VERSION += 1


def reset_tuned() -> None:
    """Drop every measured override (tests; heuristic-only runs)."""
    global _TUNED_VERSION
    _TUNED.clear()
    _TUNED_BACKEND.clear()
    _TUNED_VERSION += 1


def _tile_plan(m: int, g: int, h: int, direction: str) -> Tuple[int, int]:
    """(row_block, genes_per_grid_block) for this problem+direction:
    the measured override when one was installed, else the heuristic."""
    ent = _TUNED.get((m, g, h))
    if ent and direction in ent:
        rb, bpg = ent[direction]
        return rb, bpg * LANE_BLOCK
    return _row_block(h), _blocks_per_group(g, h) * LANE_BLOCK


def tile_candidates(m: int, g: int, h: int) -> list:
    """Legal (row_block, blocks_per_group) pairs for the autotune sweep.

    row_block must divide the caller padding quantum ROW_BLOCK (so any
    padded m stays aligned); blocks_per_group must divide the slab count
    (the grid floor-divides) and the whole per-step working set must fit
    the VMEM budget.
    """
    n_blocks = g // LANE_BLOCK
    out = []
    for rb in (128, 256, 512):
        if ROW_BLOCK % rb or m % rb:
            continue
        for bpg in range(1, n_blocks + 1):
            if n_blocks % bpg:
                continue
            if _vmem_step_bytes(bpg * LANE_BLOCK, h, rb) > _VMEM_STEP_BUDGET:
                break
            out.append((rb, bpg))
    return out


def _fwd_call(packed: jax.Array, w: jax.Array, interpret: bool,
              plan: Optional[Tuple[int, int]] = None) -> jax.Array:
    _check_aligned(packed, w)
    m, nb = packed.shape
    g, h = w.shape
    rb, gb = plan if plan is not None else _tile_plan(m, g, h, "fwd")
    return pl.pallas_call(
        functools.partial(_fwd_kernel, interpret=interpret),
        grid=(m // rb, g // gb),                 # gene blocks innermost
        in_specs=[
            pl.BlockSpec((rb, gb // 8), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gb, h), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, h), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, h), jnp.float32),
        interpret=interpret,
    )(packed, w.astype(jnp.bfloat16))


def _bwd_call(packed: jax.Array, g_out: jax.Array, interpret: bool,
              plan: Optional[Tuple[int, int]] = None) -> jax.Array:
    m, nb = packed.shape
    g, h = nb * 8, g_out.shape[1]
    rb, gb = plan if plan is not None else _tile_plan(m, g, h, "bwd")
    return pl.pallas_call(
        functools.partial(_bwd_kernel, interpret=interpret),
        grid=(g // gb, m // rb),                 # row tiles innermost
        in_specs=[
            pl.BlockSpec((rb, gb // 8), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, h), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        # Constant over the inner row sweep: the [gene_block, H] dW tile
        # stays resident and is written back once per gene block.
        out_specs=pl.BlockSpec((gb, h), lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, h), jnp.float32),
        interpret=interpret,
    )(packed, g_out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def packed_matmul(packed: jax.Array, w: jax.Array,
                  interpret: bool = False) -> jax.Array:
    """``unpack(packed) @ w`` -> [M, H] float32.

    ``packed``: [M, G//8] uint8 in :func:`pack_blockwise` layout; M must be a
    multiple of ROW_BLOCK and G of LANE_BLOCK (see :func:`pad_rows_packed`).
    ``w``: [G, H] (cast to bf16 inside; f32 accumulation on the MXU).
    Differentiable in ``w`` only (the paths are data, ref: G2Vec.py:264).
    """
    return _fwd_call(packed, w, interpret)


def _check_aligned(packed, w) -> None:
    """Loud contract: an unaligned M would silently leave grid-tail output
    rows unwritten (the grid floor-divides), an unaligned G would misalign
    the blockwise bit layout."""
    m, nb = packed.shape
    if m % ROW_BLOCK:
        raise ValueError(
            f"packed rows {m} not a multiple of ROW_BLOCK={ROW_BLOCK}; "
            "use pad_rows_packed()")
    if (nb * 8) % LANE_BLOCK or w.shape[0] != nb * 8:
        raise ValueError(
            f"gene dim {nb * 8} (w: {w.shape[0]}) not a multiple of "
            f"LANE_BLOCK={LANE_BLOCK} or inconsistent with the packed width")


def _pm_fwd(packed, w, interpret):
    # The zero-size array carries w's dtype through the residuals so the
    # bwd cotangent can match the primal exactly (strict custom_vjp dtype
    # checking on newer JAX); a bare np.dtype is not a valid pytree leaf.
    return _fwd_call(packed, w, interpret), (packed, jnp.empty((0,), w.dtype))


def _pm_bwd(interpret, res, g):
    packed, w_proto = res
    dw = _bwd_call(packed, g.astype(jnp.bfloat16), interpret)
    # float0 is THE cotangent type for integer primals; the packed bits are
    # data, not parameters (ref: G2Vec.py:264 — X is fed, never trained).
    d_packed = np.zeros(packed.shape, dtype=jax.dtypes.float0)
    return d_packed, dw.astype(w_proto.dtype)


packed_matmul.defvjp(_pm_fwd, _pm_bwd)


def packed_matmul_available(m: int, g: int, h: int,
                            backend: Optional[str] = None) -> bool:
    """True when the fused kernel supports/benefits this problem.

    Requires: TPU backend, lane-aligned dims, and a minimum (one lane
    block) grid step's whole working set within the VMEM budget. The gene
    axis tiles, so G is unbounded; the working-set model caps H at 1024.
    """
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return False
    if h % 128 or g % LANE_BLOCK:
        return False
    return _vmem_step_bytes(LANE_BLOCK, h, _row_block(h)) <= _VMEM_STEP_BUDGET


def pad_rows_packed(packed: np.ndarray, row_block: int = ROW_BLOCK) -> np.ndarray:
    """Zero-pad packed rows to a multiple of the kernel row tile."""
    m = packed.shape[0]
    target = ((m + row_block - 1) // row_block) * row_block
    if target == m:
        return packed
    pad = np.zeros((target - m, packed.shape[1]), dtype=packed.dtype)
    return np.concatenate([packed, pad], axis=0)


# ---------------------------------------------------------------------------
# Measured autotune (the --kernel-autotune flag): sweep the legal
# (row_block, blocks_per_group) pairs at the trainer's exact shapes and
# install the fastest, instead of trusting the VMEM-model heuristic's
# hardcoded 512/256 row tile. Results persist in the --cache-dir tier
# (<dir>/autotune/packed_matmul.json) so repeat runs skip the sweep.
# ---------------------------------------------------------------------------

def _autotune_backend_tag(interpret: bool) -> str:
    """Backend signature baked into every persisted key: CPU-interpret
    timings must never be served to a TPU run (or across TPU gens)."""
    if interpret:
        return "interpret"
    return f"tpu:{os.environ.get('PALLAS_AXON_TPU_GEN', 'unknown')}"


def _autotune_key(m: int, g: int, h: int, interpret: bool) -> str:
    return (f"schema={AUTOTUNE_SCHEMA};m={m};g={g};h={h};"
            f"backend={_autotune_backend_tag(interpret)}")


def _read_tune_file(path: str) -> dict:
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return {}
    if rec.get("schema") != AUTOTUNE_SCHEMA:
        return {}        # stale layout/kernel generation: re-tune
    entries = rec.get("entries")
    return entries if isinstance(entries, dict) else {}


def load_tuned(cache_path: Optional[str], m: int, g: int, h: int,
               interpret: bool = False) -> Optional[dict]:
    """Install the persisted plan for this exact problem+backend, if any.

    Returns the entry (with ``source="cache"``) on a hit, None on a miss
    or any stale/unreadable record — the caller then measures afresh.
    """
    from g2vec_tpu.cache import record_cache_event

    def _miss():
        record_cache_event("autotune", "miss")
        return None

    if not cache_path or not os.path.exists(cache_path):
        return _miss()
    ent = _read_tune_file(cache_path).get(_autotune_key(m, g, h, interpret))
    if not isinstance(ent, dict) or "fwd" not in ent or "bwd" not in ent:
        return _miss()
    try:
        plans = {d: (int(ent[d][0]), int(ent[d][1])) for d in ("fwd", "bwd")}
    except (TypeError, ValueError, IndexError, KeyError):
        return _miss()
    legal = set(tile_candidates(m, g, h))
    if any(p not in legal for p in plans.values()):
        return _miss()   # e.g. recorded against a different VMEM budget
    _install_tuned(m, g, h, plans, _autotune_backend_tag(interpret))
    record_cache_event("autotune", "hit")
    return {**ent, "source": "cache"}


def autotune_packed_matmul(m: int, g: int, h: int, *,
                           interpret: bool = False, iters: int = 5,
                           cache_path: Optional[str] = None,
                           force: bool = False) -> dict:
    """Measure every legal tile plan at (m, g, h), install + persist the best.

    ``m`` must already be padded to a ROW_BLOCK multiple and ``g`` to a
    LANE_BLOCK multiple (the trainer's _plan_layout numbers). Returns
    ``{"fwd": (rb, bpg), "bwd": (rb, bpg), "ms": {...}, "source": ...}``.
    A verified persisted entry short-circuits the sweep unless ``force``.
    """
    if m % ROW_BLOCK or g % LANE_BLOCK or h % 128:
        raise ValueError(
            f"autotune needs padded shapes (m%{ROW_BLOCK}, g%{LANE_BLOCK}, "
            f"h%128 all zero), got m={m} g={g} h={h}")
    from g2vec_tpu.cache import record_cache_event

    if not force:
        # In-memory hit FIRST, and WITHOUT a token bump: the overlap warm
        # path already swept this shape in this process, and bumping the
        # token here would invalidate the very executable it warmed.
        ent = _TUNED.get((m, g, h))
        if ent is not None and _TUNED_BACKEND.get((m, g, h)) \
                == _autotune_backend_tag(interpret) \
                and {"fwd", "bwd"} <= set(ent):
            record_cache_event("autotune", "hit")
            return {"fwd": list(ent["fwd"]), "bwd": list(ent["bwd"]),
                    "source": "memory"}
        hit = load_tuned(cache_path, m, g, h, interpret)
        if hit is not None:
            return hit
    record_cache_event("autotune", "sweep")

    cands = tile_candidates(m, g, h)
    if not cands:
        raise ValueError(f"no legal tile plan fits the VMEM budget at "
                         f"m={m} g={g} h={h}")
    rng = np.random.default_rng(0)
    packed = jnp.asarray(rng.integers(0, 256, size=(m, g // 8),
                                      dtype=np.uint8))
    w = jnp.asarray(rng.standard_normal((g, h)).astype(np.float32))
    g_out = jnp.asarray(rng.standard_normal((m, h)).astype(np.float32)
                        ).astype(jnp.bfloat16)

    def clock(fn) -> float:
        jax.block_until_ready(fn())          # compile outside the window
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn()
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    ms: Dict[str, float] = {}
    best = {}
    for direction, run in (
            ("fwd", lambda plan: jax.jit(
                lambda p, ww: _fwd_call(p, ww, interpret, plan))(packed, w)),
            ("bwd", lambda plan: jax.jit(
                lambda p, gg: _bwd_call(p, gg, interpret, plan))(packed,
                                                                 g_out))):
        best_ms, best_plan = None, None
        for rb, bpg in cands:
            plan = (rb, bpg * LANE_BLOCK)
            t = clock(lambda: run(plan))
            ms[f"{direction}:rb{rb}:bpg{bpg}"] = round(t, 4)
            if best_ms is None or t < best_ms:
                best_ms, best_plan = t, (rb, bpg)
        best[direction] = best_plan
        ms[f"{direction}:best_ms"] = round(best_ms, 4)

    _install_tuned(m, g, h, best, _autotune_backend_tag(interpret))
    entry = {"fwd": list(best["fwd"]), "bwd": list(best["bwd"]), "ms": ms,
             "heuristic": {
                 "fwd": [_row_block(h), _blocks_per_group(g, h)],
                 "bwd": [_row_block(h), _blocks_per_group(g, h)]},
             "source": "measured"}
    if cache_path:
        entries = _read_tune_file(cache_path) if os.path.exists(cache_path) \
            else {}
        entries[_autotune_key(m, g, h, interpret)] = {
            k: v for k, v in entry.items() if k != "source"}
        from g2vec_tpu.utils.integrity import write_json_atomic

        os.makedirs(os.path.dirname(cache_path) or ".", exist_ok=True)
        write_json_atomic(cache_path,
                          {"schema": AUTOTUNE_SCHEMA, "entries": entries})
    return entry


def describe_tiles(m: int, g: int, h: int) -> dict:
    """The tile plan the next (m, g, h) kernel call will actually use —
    for the bench breakdown's ``kernel_tiles`` attribution field."""
    tuned = _TUNED.get((m, g, h))
    out = {}
    for direction in ("fwd", "bwd"):
        rb, gb = _tile_plan(m, g, h, direction)
        out[direction] = {"row_block": rb, "blocks_per_group": gb // LANE_BLOCK,
                          "source": ("autotuned" if tuned
                                     and direction in tuned else "heuristic")}
    return out
