"""Pallas TPU kernel: matmul over a bit-packed multi-hot matrix.

The trainer's hot op is ``X @ W_ih`` where X is a 0/1 multi-hot path matrix
(ref: the CBOW input, G2Vec.py:238-239). Storing X densely in bf16 costs
~550 MB of HBM at example scale and every epoch re-reads it three times
(train fwd, dW, val eval — the train eval rides the next grad forward
after trainer.py's eval-train fold; the reference re-read it a fourth
time). This kernel keeps X **bit-packed**
(uint8, 8 genes/byte — 16x smaller) in HBM and unpacks tiles on the fly in
VMEM, fused into the MXU matmul, so the HBM traffic for X drops 16x. The
packed-vs-XLA-dense speedup at the trainer's exact fwd shape is a MEASURED
bench metric, not a docstring number: ``packed_matmul_vs_xla_dense`` in the
driver's BENCH_r{N}.json (bench.py stage 3; interactive spot checks on a
v5e chip saw ~0.34 ms vs ~2.7 ms at 36864 x 8192 x 128).

Layout: genes are packed **blockwise** (`pack_blockwise`): within each
``LANE_BLOCK``-gene block, gene offset ``j = c + k*(LANE_BLOCK//8)`` lives in
bit ``k`` (MSB-first) of byte ``c``. This is exactly the layout produced by
``pltpu.repeat(bytes, 8, axis=1)`` (tile-style repeat) followed by a
per-column shift — the unpack is three VPU ops per element with the shift
array hoisted out of the chunk loop (the hoist alone is worth 5x; Mosaic
does not CSE the iota across `lax.fori_loop` iterations).

Both directions are provided and glued with ``jax.custom_vjp``, each a 2-D
grid over (row tiles x gene blocks) so NO whole-matrix VMEM resident caps
the problem size (round-1 verdict: the old whole-[G,H] bwd accumulator
excluded hidden=1024 at any realistic G):
  - forward  ``unpack(P) @ W``    — grid (rows, gene blocks), gene blocks
    innermost; the [ROW_BLOCK, H] output tile stays VMEM-resident across a
    row's gene blocks (its index map is constant there) and accumulates;
  - backward ``unpack(P).T @ G``  — grid (gene blocks, rows), rows
    innermost; the [gene_block, H] dW tile stays resident across row steps.

The gene block and the row tile adapt to H via a whole-working-set VMEM
model (``_vmem_step_bytes``: resident tile + double-buffered streamed tiles
+ unpack temporaries), so G can grow without bound and H up to 1024 — the
shapes of every BASELINE config, where the old whole-table kernel stopped
at G*H*4 <= 8 MB.

Use ``packed_matmul_available()`` to gate: it requires a TPU backend (or
``interpret=True`` for CPU tests), lane-aligned shapes, and a minimum grid
step within the VMEM budget.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Gene-axis block: the unit of the blockwise bit layout and of the in-kernel
# chunk loop. 1024 genes -> 128 byte lanes, exactly one lane tile.
LANE_BLOCK = 1024
_LB_BYTES = LANE_BLOCK // 8
# Row padding quantum (callers pad row counts to this); the kernels
# themselves may run a SMALLER row tile when H is large (_row_block) — 512
# is a multiple of every effective tile, so padded inputs stay aligned.
ROW_BLOCK = 512

# Whole-working-set VMEM budget per grid step: resident tile + streamed
# (double-buffered) tiles + unpack temporaries, against the ~16 MB/core of
# v4/v5e with slack for Mosaic's own spills.
_VMEM_STEP_BUDGET = 14 * 1024 * 1024


def _row_block(h: int) -> int:
    """Effective row tile: streamed-tile VMEM scales with rows*H, so rows
    shrink as H grows (512 stays the outer padding quantum)."""
    if h <= 256:
        return 512
    return 256


def _vmem_step_bytes(gb: int, h: int, rb: int) -> int:
    """Worst-direction VMEM working set of one grid step (bytes).

    Counts, per the kernel bodies below: the resident f32 tile (fwd output /
    bwd dW), double-buffered streamed tiles (W bf16 in fwd; g_out bf16 in
    bwd — _pm_bwd casts the cotangent BEFORE the call, so the kernel's
    astype is a no-op), double-buffered packed tiles, the per-slab dot
    output (bwd), the separate f32 acc (fwd), and the unpack temporaries
    (rep int32 + hoisted shift int32 + x bf16 = 10 bytes/element)."""
    unpack = rb * LANE_BLOCK * 10
    p_tiles = 2 * rb * (gb // 8)
    bwd = (gb * h * 4 + 2 * rb * h * 2
           + LANE_BLOCK * h * 4 + p_tiles + unpack)
    fwd = 2 * gb * h * 2 + 2 * rb * h * 4 + p_tiles + unpack
    return max(bwd, fwd)


def pack_blockwise(x: np.ndarray, block: int = LANE_BLOCK) -> np.ndarray:
    """[M, G] 0/1 -> [M, G//8] uint8 in the kernel's blockwise bit layout.

    Within each ``block``-gene slab: gene offset ``j = c + k*(block//8)``
    is bit ``k`` (MSB-first) of byte ``c``. G must be a multiple of block.
    """
    m, g = x.shape
    if g % block:
        raise ValueError(f"n_genes {g} not a multiple of pack block {block}")
    bb = block // 8
    xr = np.ascontiguousarray(
        x.reshape(m, g // block, 8, bb).transpose(0, 1, 3, 2))
    return np.packbits(xr.astype(bool), axis=3, bitorder="big").reshape(m, g // 8)


def unpack_blockwise(packed: np.ndarray, block: int = LANE_BLOCK) -> np.ndarray:
    """Host-side inverse of :func:`pack_blockwise` (tests, checkpoints)."""
    m, nb = packed.shape
    g = nb * 8
    bb = block // 8
    bits = np.unpackbits(packed.reshape(m, g // block, bb, 1), axis=3,
                         bitorder="big")
    return bits.transpose(0, 1, 3, 2).reshape(m, g)


def _shift_array(rows: int) -> jax.Array:
    """[rows, LANE_BLOCK] int32: MSB-first shift for each unpacked column."""
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, LANE_BLOCK), 1)
    return 7 - col // _LB_BYTES


def _unpack_tile(p_chunk: jax.Array, shift: jax.Array) -> jax.Array:
    """[rows, LB_BYTES] uint8 -> [rows, LANE_BLOCK] bf16 0/1."""
    rep = pltpu.repeat(p_chunk.astype(jnp.int32), 8, axis=1)
    return ((rep >> shift) & 1).astype(jnp.bfloat16)


def _blocks_per_group(g: int, h: int) -> int:
    """LANE_BLOCK slabs per gene block: as many as keep the whole per-step
    working set within budget, while dividing G's slab count evenly (the
    grid floor-divides; an uneven tail would be dropped)."""
    n_blocks = g // LANE_BLOCK
    rb = _row_block(h)
    cap = 1
    while (cap < n_blocks
           and _vmem_step_bytes((cap + 1) * LANE_BLOCK, h, rb)
           <= _VMEM_STEP_BUDGET):
        cap += 1
    bpg = min(n_blocks, cap)
    while n_blocks % bpg:
        bpg -= 1
    return bpg


def _fwd_kernel(p_ref, w_ref, o_ref):
    nchunks = w_ref.shape[0] // LANE_BLOCK
    shift = _shift_array(p_ref.shape[0])

    # Gene blocks are the INNER grid dim: the output tile's index map is
    # constant across them, so it stays VMEM-resident and accumulates.
    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(c, acc):
        x = _unpack_tile(p_ref[:, pl.ds(c * _LB_BYTES, _LB_BYTES)], shift)
        wc = w_ref[pl.ds(c * LANE_BLOCK, LANE_BLOCK), :]
        return acc + jax.lax.dot_general(
            x, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jnp.zeros((p_ref.shape[0], w_ref.shape[1]), jnp.float32)
    o_ref[:] += jax.lax.fori_loop(0, nchunks, body, acc)


def _bwd_kernel(p_ref, g_ref, o_ref):
    nchunks = o_ref.shape[0] // LANE_BLOCK
    shift = _shift_array(p_ref.shape[0])

    # Row tiles are the INNER grid dim here: the [gene_block, H] dW tile
    # stays resident across a gene block's row sweep.
    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    gtile = g_ref[:].astype(jnp.bfloat16)

    def body(c, _):
        x = _unpack_tile(p_ref[:, pl.ds(c * _LB_BYTES, _LB_BYTES)], shift)
        sl = pl.ds(c * LANE_BLOCK, LANE_BLOCK)
        o_ref[sl, :] += jax.lax.dot_general(
            x, gtile, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return 0

    jax.lax.fori_loop(0, nchunks, body, 0)


def _fwd_call(packed: jax.Array, w: jax.Array, interpret: bool) -> jax.Array:
    _check_aligned(packed, w)
    m, nb = packed.shape
    g, h = w.shape
    gb = _blocks_per_group(g, h) * LANE_BLOCK    # genes per grid block
    rb = _row_block(h)                           # m % 512 == 0 => m % rb == 0
    return pl.pallas_call(
        _fwd_kernel,
        grid=(m // rb, g // gb),                 # gene blocks innermost
        in_specs=[
            pl.BlockSpec((rb, gb // 8), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gb, h), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, h), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, h), jnp.float32),
        interpret=interpret,
    )(packed, w.astype(jnp.bfloat16))


def _bwd_call(packed: jax.Array, g_out: jax.Array, interpret: bool) -> jax.Array:
    m, nb = packed.shape
    g, h = nb * 8, g_out.shape[1]
    gb = _blocks_per_group(g, h) * LANE_BLOCK
    rb = _row_block(h)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(g // gb, m // rb),                 # row tiles innermost
        in_specs=[
            pl.BlockSpec((rb, gb // 8), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, h), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        # Constant over the inner row sweep: the [gene_block, H] dW tile
        # stays resident and is written back once per gene block.
        out_specs=pl.BlockSpec((gb, h), lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, h), jnp.float32),
        interpret=interpret,
    )(packed, g_out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def packed_matmul(packed: jax.Array, w: jax.Array,
                  interpret: bool = False) -> jax.Array:
    """``unpack(packed) @ w`` -> [M, H] float32.

    ``packed``: [M, G//8] uint8 in :func:`pack_blockwise` layout; M must be a
    multiple of ROW_BLOCK and G of LANE_BLOCK (see :func:`pad_rows_packed`).
    ``w``: [G, H] (cast to bf16 inside; f32 accumulation on the MXU).
    Differentiable in ``w`` only (the paths are data, ref: G2Vec.py:264).
    """
    return _fwd_call(packed, w, interpret)


def _check_aligned(packed, w) -> None:
    """Loud contract: an unaligned M would silently leave grid-tail output
    rows unwritten (the grid floor-divides), an unaligned G would misalign
    the blockwise bit layout."""
    m, nb = packed.shape
    if m % ROW_BLOCK:
        raise ValueError(
            f"packed rows {m} not a multiple of ROW_BLOCK={ROW_BLOCK}; "
            "use pad_rows_packed()")
    if (nb * 8) % LANE_BLOCK or w.shape[0] != nb * 8:
        raise ValueError(
            f"gene dim {nb * 8} (w: {w.shape[0]}) not a multiple of "
            f"LANE_BLOCK={LANE_BLOCK} or inconsistent with the packed width")


def _pm_fwd(packed, w, interpret):
    # The zero-size array carries w's dtype through the residuals so the
    # bwd cotangent can match the primal exactly (strict custom_vjp dtype
    # checking on newer JAX); a bare np.dtype is not a valid pytree leaf.
    return _fwd_call(packed, w, interpret), (packed, jnp.empty((0,), w.dtype))


def _pm_bwd(interpret, res, g):
    packed, w_proto = res
    dw = _bwd_call(packed, g.astype(jnp.bfloat16), interpret)
    # float0 is THE cotangent type for integer primals; the packed bits are
    # data, not parameters (ref: G2Vec.py:264 — X is fed, never trained).
    d_packed = np.zeros(packed.shape, dtype=jax.dtypes.float0)
    return d_packed, dw.astype(w_proto.dtype)


packed_matmul.defvjp(_pm_fwd, _pm_bwd)


def packed_matmul_available(m: int, g: int, h: int,
                            backend: Optional[str] = None) -> bool:
    """True when the fused kernel supports/benefits this problem.

    Requires: TPU backend, lane-aligned dims, and a minimum (one lane
    block) grid step's whole working set within the VMEM budget. The gene
    axis tiles, so G is unbounded; the working-set model caps H at 1024.
    """
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return False
    if h % 128 or g % LANE_BLOCK:
        return False
    return _vmem_step_bytes(LANE_BLOCK, h, _row_block(h)) <= _VMEM_STEP_BUDGET


def pad_rows_packed(packed: np.ndarray, row_block: int = ROW_BLOCK) -> np.ndarray:
    """Zero-pad packed rows to a multiple of the kernel row tile."""
    m = packed.shape[0]
    target = ((m + row_block - 1) // row_block) * row_block
    if target == m:
        return packed
    pad = np.zeros((target - m, packed.shape[1]), dtype=packed.dtype)
    return np.concatenate([packed, pad], axis=0)
