"""L3 — vectorized weighted random walks on device.

Reference semantics (generate_pathSet / generate_randomPath,
G2Vec.py:324-352), reproduced distributionally:

- every gene is a start node, ``numRepetition`` times (G2Vec.py:348-349);
- a path holds at most ``lenPath`` nodes (the append happens at the top of
  the step loop, G2Vec.py:331-332 — the node sampled on the final iteration
  is never appended);
- no revisiting: sampling weights of every node already on the path are
  zeroed (``prob[path] = 0.``, G2Vec.py:336);
- the next node is Categorical(weights / sum) (G2Vec.py:338-341);
- a walker stops early when every unvisited neighbor has weight 0
  ("dead end", G2Vec.py:342-344);
- a finished path is canonicalized as its sorted node tuple and deduplicated
  through a set (G2Vec.py:345, 351).

TPU design — the reference walks one node at a time in Python with an
O(n_genes) ``deepcopy`` per step (G2Vec.py:334; ~4.5e10 element touches per
group at example scale, its self-declared "most time consuming step").
Here ALL walkers advance in lockstep inside one jitted ``lax.scan``:

- walker state is (visited [W, G] bool, current [W] int32, alive [W] bool);
- the per-step transition row gather ``adj[current]`` and the visited mask
  are dense [W, G] ops (HBM-bandwidth bound, MXU-free, XLA fuses the
  mask/normalize/sample chain);
- the categorical draw is Gumbel-max over masked log-weights — exactly
  Categorical(w/Σw) without materializing the normalization;
- a dead-ended walker freezes (alive gate) and its state is carried
  unchanged through the remaining steps — fixed trip count, no dynamic
  control flow, one compiled program;
- the final visited mask [W, G] IS the path's canonical encoding: a
  multi-hot row over genes == the sorted-tuple-of-unique-nodes set form
  (G2Vec.py:345), so dedup is row-dedup (packed to bytes host-side).

The walk itself never leaves the device; only the packed bool masks cross to
host for set semantics (dedup / common-path drop), which are
order-sensitive-free and cheap (n_paths × G/8 bytes).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30  # large-negative instead of -inf: keeps argmax well-defined


def _walk(n_genes: int, candidates, starts: jax.Array, key: jax.Array,
          len_path: int) -> jax.Array:
    """Shared walk scaffold for the dense and sparse transition formats.

    ``candidates(current, visited) -> (w, cand)`` supplies, per step, the
    [W, K] sampling weights (already zeroed for visited/padding targets) and
    the [W, K] global gene index of each slot (``None`` when slots ARE gene
    indices, i.e. K == G). Everything else — per-walker key fan-out,
    Gumbel-max categorical draw, dead-end freeze, visited bookkeeping, the
    fixed-trip-count scan — is format-independent and lives only here, so
    the two walkers cannot drift semantically.
    """
    n_walkers = starts.shape[0]
    if key.ndim == 0:
        walker_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(n_walkers))
    else:
        walker_keys = key

    visited0 = jax.nn.one_hot(starts, n_genes, dtype=jnp.bool_)
    state0 = (visited0, starts.astype(jnp.int32),
              jnp.ones((n_walkers,), dtype=jnp.bool_))

    def step(state, step_idx):
        visited, current, alive = state
        w, cand = candidates(current, visited)             # [W, K] each
        can_move = alive & (w.sum(axis=1) > 0.0)           # dead-end freeze
        logits = jnp.where(w > 0.0, jnp.log(jnp.where(w > 0.0, w, 1.0)), NEG_INF)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(jax.random.fold_in(k, step_idx),
                                        (w.shape[1],)))(walker_keys)
        slot = jnp.argmax(logits + gumbel, axis=1)
        if cand is None:
            nxt = slot.astype(jnp.int32)
        else:
            nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
        current = jnp.where(can_move, nxt, current)
        moved = jax.nn.one_hot(nxt, n_genes, dtype=jnp.bool_) & can_move[:, None]
        visited = visited | moved
        return (visited, current, can_move), None

    # len_path nodes total = the start node + (len_path - 1) sampled moves.
    (visited, _, _), _ = jax.lax.scan(
        step, state0, jnp.arange(max(len_path - 1, 0)))
    return visited


@partial(jax.jit, static_argnames=("len_path",))
def random_walks(adj: jax.Array, starts: jax.Array, key: jax.Array,
                 len_path: int) -> jax.Array:
    """Walk |starts| walkers for <= len_path nodes; return visited [W, G] bool.

    ``adj``: [G, G] float32 non-negative directed transition weights (zero =
    no edge). ``starts``: [W] int32 start nodes. ``key`` is either ONE PRNG
    key (per-walker keys derived by position) or a [W] array of per-walker
    keys — the latter is what makes :func:`generate_path_set` invariant to
    ``walker_batch``: each walker's stream is keyed by its global identity,
    not by which launch it rode in. The returned multi-hot rows are the
    canonical path encodings (see module docstring).
    """

    def candidates(current, visited):
        w = jnp.where(visited, 0.0, adj[current])          # no revisit
        return w, None                                     # slots == genes

    return _walk(adj.shape[0], candidates, starts, key, len_path)


@partial(jax.jit, static_argnames=("len_path",))
def random_walks_sparse(nbr_idx: jax.Array, nbr_w: jax.Array,
                        starts: jax.Array, key: jax.Array,
                        len_path: int) -> jax.Array:
    """Sparse-transition twin of :func:`random_walks`.

    ``nbr_idx``/``nbr_w``: [G, D] padded out-neighbor lists from
    :func:`g2vec_tpu.ops.graph.neighbor_table` (padding = weight 0). Same
    walk semantics, but each step works on [W, D] instead of [W, G]:
    gather the current nodes' neighbor rows, mask visited targets via a
    per-row take_along_axis into the visited table, Gumbel-max over the D
    slots, then map the winning slot back to its global gene index. At the
    reference scale D is ~2 orders of magnitude smaller than G, and the
    O(W*G) work that remains (the visited-bit scatter) is a single one-hot
    OR. Returns visited [W, G] bool — identical encoding to the dense path.
    """
    def candidates(current, visited):
        cand = nbr_idx[current]                            # [W, D] gather
        seen = jnp.take_along_axis(visited, cand, axis=1)  # [W, D]
        w = jnp.where(seen, 0.0, nbr_w[current])           # no revisit (+pads stay 0)
        return w, cand

    return _walk(nbr_idx.shape[0], candidates, starts, key, len_path)


# shard_map walk programs are built per (mesh, shapes) — cache them or every
# repetition re-traces the whole scan (the jit cache keys on fn identity).
_SHARDED_WALK_CACHE: dict = {}


def _sharded_sparse_walk_fn(mesh, n_genes: int, len_path: int):
    """Sparse walk with the neighbor tables ROW-SHARDED over 'model'.

    Round-1 gap (VERDICT.md #9): under a mesh the 2*G*D tables were
    replicated per device, defeating the model axis at 40k+-gene scale.
    Here each model shard stores only its table rows; the per-step row
    gather becomes an ownership-masked local gather + psum over 'model'
    (each row has exactly one owner, so the sum reconstructs exactly
    ``nbr_idx[current]`` / ``nbr_w[current]`` in the same slot order — the
    Gumbel draws, and therefore the sampled paths, are bit-identical to the
    unsharded walker for the same keys). Walkers stay DP over 'data';
    model shards duplicate the (cheap) per-walker sampling compute and
    carry identical visited state.
    """
    from jax.sharding import PartitionSpec as P

    from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    def walk(nbr_idx_local, nbr_w_local, starts, keys):
        rows_per_shard = nbr_idx_local.shape[0]
        base = jax.lax.axis_index(MODEL_AXIS) * rows_per_shard

        def candidates(current, visited):
            local = current - base
            own = (local >= 0) & (local < rows_per_shard)
            safe = jnp.clip(local, 0, rows_per_shard - 1)
            cand = jnp.where(own[:, None], nbr_idx_local[safe], 0)
            w = jnp.where(own[:, None], nbr_w_local[safe], 0.0)
            cand = jax.lax.psum(cand, MODEL_AXIS)
            w = jax.lax.psum(w, MODEL_AXIS)
            seen = jnp.take_along_axis(visited, cand, axis=1)
            return jnp.where(seen, 0.0, w), cand

        return _walk(n_genes, candidates, starts, keys, len_path)

    sharded = jax.shard_map(
        walk, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None),
                  P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS, None),
        # The scan carry mixes constants (alive mask init) with
        # data-varying state; the VMA check rejects that mix even though
        # the program is correct (same pattern as the trainer's
        # pallas-under-shard_map call).
        check_vma=False)
    return jax.jit(sharded)


# Replicating the neighbor tables is FASTER (zero collectives per step)
# whenever they fit comfortably: shard only past this per-device size, where
# the memory win pays for the two per-step [W, D] psums over 'model'.
SHARD_TABLE_BYTES = 128 * 1024 * 1024


def _get_sharded_walk_fn(mesh, n_genes: int, len_path: int):
    key = (mesh, n_genes, len_path)
    fn = _SHARDED_WALK_CACHE.get(key)
    if fn is None:
        fn = _sharded_sparse_walk_fn(mesh, n_genes, len_path)
        while len(_SHARDED_WALK_CACHE) >= 8:
            _SHARDED_WALK_CACHE.pop(next(iter(_SHARDED_WALK_CACHE)))
        _SHARDED_WALK_CACHE[key] = fn
    return fn


def generate_path_set(adj, key: jax.Array, *, len_path: int, reps: int,
                      starts: Optional[np.ndarray] = None,
                      walker_batch: int = 0,
                      mesh_ctx=None,
                      shard_tables: Optional[bool] = None) -> Set[bytes]:
    """All-sources x reps walks -> set of packed multi-hot path rows.

    Mirrors generate_pathSet (G2Vec.py:324-352): every gene is a start node,
    ``reps`` times; results are set-deduplicated. Each element is
    ``np.packbits`` of the [G] bool row (fixed G; unpack with
    :func:`unpack_paths`).

    ``adj`` is either a dense [G, G] transition matrix or a
    ``(nbr_idx [G, D], nbr_w [G, D])`` neighbor-table pair from
    :func:`g2vec_tpu.ops.graph.neighbor_table` — the sparse form is the
    TPU-efficient default for the pipeline (O(W*D) per step, no dense G^2
    HBM residency). ``walker_batch`` caps walkers per device launch (0 = one
    full repetition, i.e. n_genes walkers). Transition tables are
    transferred once; each batch returns only its packed masks. The result
    is INVARIANT to ``walker_batch``: every walker's PRNG stream is keyed by
    its (repetition, global walker index), not by its launch batch, so the
    memory knob never changes which paths a given --seed produces. (It is
    NOT invariant to the dense/sparse choice — the two draw differently
    shaped Gumbel noise — but each is deterministic per seed.)

    ``mesh_ctx``: walkers are embarrassingly data-parallel — the walker axis
    shards over 'data'. Sparse tables additionally ROW-SHARD over 'model'
    when the mesh has one AND they are big enough to matter
    (``shard_tables``: None = auto at SHARD_TABLE_BYTES, or force with
    True/False). Each shard then stores 2*G*D/M values; the per-step gather
    becomes an ownership-masked local gather + psum that reconstructs the
    exact unsharded candidate rows (:func:`_sharded_sparse_walk_fn`), so
    the path set stays bit-identical. Small tables replicate — the walk
    compiles to zero collectives. Result-invariant vs single-device either
    way: shard padding walkers are dropped host-side and each walker's PRNG
    stream is its own.
    """
    from jax.sharding import PartitionSpec as P

    from g2vec_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, MeshContext,
                                         pad_to_multiple)

    sparse = isinstance(adj, tuple)
    ctx = mesh_ctx if mesh_ctx is not None else MeshContext(mesh=None)
    data_dim = 1 if ctx.mesh is None else ctx.mesh.shape[DATA_AXIS]
    model_dim = 1 if ctx.mesh is None else ctx.mesh.shape[MODEL_AXIS]
    walker_spec = P(DATA_AXIS)           # 1-D walker axis, rows over 'data'
    if sparse:
        nbr_idx, nbr_w = adj
        n_genes = int(nbr_idx.shape[0])
        if shard_tables is None:
            # Auto: replicate small tables (collective-free walk); shard
            # once they are big enough that the memory win matters.
            shard_tables = (model_dim > 1
                            and nbr_idx.size * 8 > SHARD_TABLE_BYTES)
        if shard_tables and model_dim > 1:
            # Row-shard the tables over 'model' (zero-padded to split
            # evenly; pad rows are unreachable — nothing points at gene
            # ids >= n_genes, and their own weights are 0).
            g_pad = pad_to_multiple(n_genes, model_dim)
            nbr_idx = np.pad(np.asarray(nbr_idx),
                             ((0, g_pad - n_genes), (0, 0)))
            nbr_w = np.pad(np.asarray(nbr_w),
                           ((0, g_pad - n_genes), (0, 0)))
            table_spec = P(MODEL_AXIS, None)
        else:
            table_spec = P()
        table = (ctx.put(jnp.asarray(nbr_idx, dtype=jnp.int32), table_spec),
                 ctx.put(jnp.asarray(nbr_w, dtype=jnp.float32), table_spec))
    else:
        n_genes = int(adj.shape[0])
        table = ctx.put(jnp.asarray(adj, dtype=jnp.float32), P())
    if starts is None:
        starts = np.arange(n_genes, dtype=np.int32)
    starts = np.asarray(starts, dtype=np.int32)
    batch = walker_batch if walker_batch > 0 else starts.size

    paths: Set[bytes] = set()
    for rep_key in jax.random.split(key, reps):
        all_keys = jax.vmap(lambda i: jax.random.fold_in(rep_key, i))(
            jnp.arange(starts.size))
        for lo in range(0, starts.size, batch):
            chunk = starts[lo:lo + batch]
            chunk_keys = all_keys[lo:lo + batch]
            n_real = chunk.size
            # Shard-even padding: duplicate walker 0, drop its rows after.
            n_pad = pad_to_multiple(n_real, data_dim)
            if n_pad != n_real:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[:1], n_pad - n_real)])
                chunk_keys = jnp.concatenate(
                    [chunk_keys,
                     jnp.repeat(chunk_keys[:1], n_pad - n_real, axis=0)])
            chunk = ctx.put(jnp.asarray(chunk), walker_spec)
            chunk_keys = ctx.put(chunk_keys, walker_spec)
            if sparse and shard_tables and model_dim > 1:
                fn = _get_sharded_walk_fn(ctx.mesh, n_genes, len_path)
                visited = fn(table[0], table[1], chunk, chunk_keys)
            elif sparse:
                visited = random_walks_sparse(table[0], table[1], chunk,
                                              chunk_keys, len_path)
            else:
                visited = random_walks(table, chunk, chunk_keys, len_path)
            # fetch_global, not np.asarray: under a multi-process mesh the
            # visited rows span devices other processes own.
            from g2vec_tpu.parallel.distributed import fetch_global

            packed = np.packbits(fetch_global(visited)[:n_real], axis=1)
            paths.update(row.tobytes() for row in packed)
    return paths


def unpack_paths(packed: Sequence[bytes], n_genes: int) -> np.ndarray:
    """Packed path rows -> [N, n_genes] uint8 multi-hot (sorted for determinism).

    uint8, not int32: at reference scale (45k x 7.5k) the multi-hot matrix is
    ~340 MB this way; every consumer re-casts anyway (the trainer to its
    compute dtype, the frequency vote through numpy's promoting sum).
    """
    rows = _packed_rows(packed, n_genes)
    return np.unpackbits(rows, axis=1)[:, :n_genes]


def integrate_path_sets(path_set_good: Set[bytes], path_set_poor: Set[bytes],
                        n_genes: int, packed: bool = False,
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Drop paths common to both groups; return (multi-hot, labels).

    Reference: integrate_pathSet (G2Vec.py:310-322) — a path gene-set present
    in BOTH groups' sets carries no prognosis signal and is removed from
    both; survivors get their group index as the label. The reference's
    trailing label column is a separate array here (the trainer takes
    (paths, labels), not a glued matrix). Row order: good block then poor
    block, each sorted by packed bytes (the reference iterates Python-set
    order — nondeterministic; we pin it).

    ``packed=True`` returns the paths still bit-packed ([N, ceil(G/8)]
    uint8, np.packbits layout) — the scalable form the pipeline feeds
    straight to the trainer: the dense uint8 [N, G] matrix is never
    materialized on host (8x smaller at any scale).
    """
    common = path_set_good & path_set_poor
    fn = _packed_rows if packed else unpack_paths
    good = fn(path_set_good - common, n_genes)
    poor = fn(path_set_poor - common, n_genes)
    paths = np.concatenate([good, poor], axis=0)
    labels = np.concatenate([
        np.zeros(good.shape[0], dtype=np.int32),
        np.ones(poor.shape[0], dtype=np.int32)])
    return paths, labels


def _packed_rows(packed: Set[bytes], n_genes: int) -> np.ndarray:
    """Set of packed rows -> [N, ceil(G/8)] uint8 (sorted for determinism)."""
    nb = (n_genes + 7) // 8
    if not packed:
        return np.zeros((0, nb), dtype=np.uint8)
    rows = np.frombuffer(b"".join(sorted(packed)), dtype=np.uint8)
    return rows.reshape(len(packed), nb)


def count_gene_freq(paths: np.ndarray, labels: np.ndarray,
                    genes: Sequence[str], packed: bool = False,
                    ) -> Dict[str, int]:
    """Per-gene majority vote over the integrated path set.

    Reference: count_geneFreq (G2Vec.py:288-308) — for each gene appearing in
    at least one path, count good vs poor paths containing it; majority ->
    0/1, tie -> 2. Genes in no path are absent from the dict (callers default
    them to 2, ref: G2Vec.py:172).

    With ``packed=True``, ``paths`` is the bit-packed [N, ceil(G/8)] uint8
    form (integrate_path_sets(packed=True)); rows are expanded in bounded
    chunks so the dense matrix never materializes whole.
    """
    n_genes = len(genes)
    if packed:
        if paths.shape[1] != (n_genes + 7) // 8:
            raise ValueError(
                f"packed paths width {paths.shape[1]} inconsistent with "
                f"{n_genes} genes (expected {(n_genes + 7) // 8})")

        def colsum(block):
            total = np.zeros(n_genes, dtype=np.int64)
            for lo in range(0, block.shape[0], 4096):
                rows = np.unpackbits(block[lo:lo + 4096], axis=1)[:, :n_genes]
                total += rows.sum(axis=0, dtype=np.int64)
            return total

        good_counts = colsum(paths[labels == 0])
        poor_counts = colsum(paths[labels == 1])
    else:
        good_counts = paths[labels == 0].sum(axis=0)
        poor_counts = paths[labels == 1].sum(axis=0)
    result: Dict[str, int] = {}
    for i, g in enumerate(genes):
        fg, fp = int(good_counts[i]), int(poor_counts[i])
        if fg == 0 and fp == 0:
            continue
        result[g] = 0 if fg > fp else (1 if fg < fp else 2)
    return result
