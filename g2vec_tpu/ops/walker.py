"""L3 — vectorized weighted random walks on device (jax.random family).

PRODUCTION NOTE: this module's walkers draw from the jax.random PRNG
family and are only *statistically* equivalent to the host C++ sampler.
The production device sampler is now :mod:`g2vec_tpu.ops.device_walker`
— a CSR-native splitmix64 walker whose packed rows are BYTE-IDENTICAL
to the native sampler's (one shared walk-cache family, backend-blind
goldens). The dense [G, G] entry points here are deprecated (shimmed
with DeprecationWarning — they cannot reach production scales); the
sparse neighbor-table walker remains for mesh-sharded table experiments
and as the legacy DEVICE_FAMILY artifact reader.

Reference semantics (generate_pathSet / generate_randomPath,
G2Vec.py:324-352), reproduced distributionally:

- every gene is a start node, ``numRepetition`` times (G2Vec.py:348-349);
- a path holds at most ``lenPath`` nodes (the append happens at the top of
  the step loop, G2Vec.py:331-332 — the node sampled on the final iteration
  is never appended);
- no revisiting: sampling weights of every node already on the path are
  zeroed (``prob[path] = 0.``, G2Vec.py:336);
- the next node is Categorical(weights / sum) (G2Vec.py:338-341);
- a walker stops early when every unvisited neighbor has weight 0
  ("dead end", G2Vec.py:342-344);
- a finished path is canonicalized as its sorted node tuple and deduplicated
  through a set (G2Vec.py:345, 351).

TPU design — the reference walks one node at a time in Python with an
O(n_genes) ``deepcopy`` per step (G2Vec.py:334; ~4.5e10 element touches per
group at example scale, its self-declared "most time consuming step").
Here ALL walkers advance in lockstep inside one jitted ``lax.scan``, and the
step was rebuilt around what round-2 profiling showed on the real chip
(tools/profile_walker.py: 125 ms/step at W=G=9904, D=1024 for the original
gumbel-max step — PROFILE.md has the decomposition):

- ALL randomness is drawn OUTSIDE the scan: inverse-CDF categorical
  sampling needs ONE uniform per (walker, step), a [W, steps] array derived
  from per-walker keys — vs the original's per-step, per-walker
  ``fold_in`` + [W, D] Gumbel fan-out (W*D threefry draws per step, the
  dominant cost at D=1024);
- the categorical draw over the masked weights is inverse-CDF: cumsum the
  [W, D] candidate weights, count(cum <= u*total) — exactly
  Categorical(w/Σw), no log/exp/argmax, lane-friendly elementwise/reduce
  work only;
- the no-revisit test compares candidates against the walker's PATH LIST
  ([W, L] int32, L = len_path): ``seen[w,d] = any_l(path[w,l] == cand[w,d])``
  — a fused [W, D, L] broadcast-compare. The sparse step touches NO
  [W, G]-shaped state at all (the original gathered visited bits out of a
  [W, G] bool table with an axis-1 ``take_along_axis`` and rebuilt it with a
  one_hot OR every step); the multi-hot encoding is built ONCE after the
  scan;
- a dead-ended walker freezes (alive gate, sentinel writes) — fixed trip
  count, no dynamic control flow, one compiled program;
- the final visited mask [W, G] IS the path's canonical encoding: a
  multi-hot row over genes == the sorted-tuple-of-unique-nodes set form
  (G2Vec.py:345), so dedup is row-dedup. Rows are bit-packed ON DEVICE
  (np.packbits layout) before crossing to host — an 8x smaller transfer,
  which matters on a tunneled TPU.

Only the packed masks cross to host for set semantics (dedup / common-path
drop), which are order-free and cheap (n_paths x G/8 bytes). ``reps`` no
longer means ``reps`` sequential launches: all reps*n_genes walkers flatten
into one walker axis, split into device launches sized by an HBM
working-set model (:func:`auto_walker_batch`) — the chip sees one big
lockstep dispatch instead of ~10 small ones, and the memory knob stays
result-invariant (every walker's PRNG stream is keyed by its (repetition,
global index) identity, never by which launch it rode in).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Inverse-CDF guard: u in [0, 1-1e-6] keeps u*total strictly below total in
# float32, so the selected slot can never fall past the last positive-weight
# slot (a u*total == total rounding event would otherwise pick a
# zero-weight padding slot roughly once per ~1e7 draws).
_U_MAX = 1.0 - 1e-6


def _per_walker_uniforms(key: jax.Array, n_walkers: int, n_steps: int
                         ) -> jax.Array:
    """[n_steps, W] uniforms; walker w's column depends only on its key.

    ``key`` is one PRNG key (walker keys derived by position), a [W] key
    array, or a [W, 2] uint32 key-DATA array (jax.random.key_data form —
    what :func:`generate_path_set` ships host->device: a committed typed-key
    array cannot be device_put onto a cross-process sharding, raw uint32
    can). Either [W] form is the batch-invariant path: keys bound to global
    walker identity. Drawn once per launch — the scan body consumes a row
    per step and does zero PRNG work.
    """
    if key.ndim == 0:
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(n_walkers))
    elif key.ndim == 2:
        keys = jax.random.wrap_key_data(key)
    else:
        keys = key
    u = jax.vmap(lambda k: jax.random.uniform(
        k, (n_steps,), maxval=_U_MAX))(keys)               # [W, S]
    return u.T                                             # [S, W]


def _sample_slots(w: jax.Array, u: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Inverse-CDF categorical over the slot axis.

    ``w``: [W, K] non-negative weights (zeros = masked/padding slots);
    ``u``: [W] uniforms in [0, 1). Returns (slot [W] int32, total [W]).
    Exactly Categorical(w/Σw): P(slot=j) = w_j/Σw for every positive slot,
    0 for zero-weight slots (cum is flat across them, so count(cum <= t)
    skips straight past). total == 0 marks a dead end; the caller freezes
    those walkers and the (arbitrary) slot value is never used.
    """
    cum = jnp.cumsum(w, axis=1)
    total = cum[:, -1]
    target = u * total
    slot = jnp.sum(cum <= target[:, None], axis=1).astype(jnp.int32)
    return jnp.minimum(slot, w.shape[1] - 1), total


def _select_slot(values: jax.Array, slot: jax.Array):
    """values[w, slot[w]] as a masked reduce — no axis-1 gather."""
    sel = jnp.arange(values.shape[1])[None, :] == slot[:, None]
    return jnp.sum(jnp.where(sel, values, 0), axis=1)


def _visited_from_path_list(path_list: jax.Array, n_genes: int) -> jax.Array:
    """[W, L] node lists (-1 = empty) -> [W, G] bool multi-hot, built once.

    One one_hot-OR pass per path slot (L passes total) — the same work the
    original step did EVERY step, done once after the scan. one_hot maps the
    -1 sentinel to an all-zero row.
    """
    def body(i, visited):
        col = jax.lax.dynamic_index_in_dim(path_list, i, axis=1,
                                           keepdims=False)
        return visited | jax.nn.one_hot(col, n_genes, dtype=jnp.bool_)

    init = jnp.zeros((path_list.shape[0], n_genes), dtype=jnp.bool_)
    return jax.lax.fori_loop(0, path_list.shape[1], body, init)


@partial(jax.jit, static_argnames=("len_path",))
def _random_walks_dense(adj: jax.Array, starts: jax.Array, key: jax.Array,
                        len_path: int) -> jax.Array:
    """Walk |starts| walkers for <= len_path nodes; return visited [W, G] bool.

    ``adj``: [G, G] float32 non-negative directed transition weights (zero =
    no edge). ``starts``: [W] int32 start nodes. ``key`` is either ONE PRNG
    key (per-walker keys derived by position) or a [W] array of per-walker
    keys — the latter is what makes :func:`generate_path_set` invariant to
    ``walker_batch``: each walker's stream is keyed by its global identity,
    not by which launch it rode in. The returned multi-hot rows are the
    canonical path encodings (see module docstring).

    Dense variant: candidate slots ARE gene indices, so the no-revisit mask
    is the visited table itself (``where(visited, 0, adj[current])`` — no
    gather) and visited updates by a one_hot OR. Used for small/test graphs
    and when no neighbor table was built; the pipeline default is
    :func:`random_walks_sparse`.
    """
    n_genes = adj.shape[0]
    n_walkers = starts.shape[0]
    n_steps = max(len_path - 1, 0)
    uniforms = _per_walker_uniforms(key, n_walkers, n_steps)

    visited0 = jax.nn.one_hot(starts, n_genes, dtype=jnp.bool_)
    state0 = (visited0, starts.astype(jnp.int32),
              jnp.ones((n_walkers,), dtype=jnp.bool_))

    def step(state, u):
        visited, current, alive = state
        w = jnp.where(visited, 0.0, adj[current])          # no revisit
        slot, total = _sample_slots(w, u)
        w_sel = _select_slot(w, slot)
        can_move = alive & (total > 0.0) & (w_sel > 0.0)
        nxt = jnp.where(can_move, slot, current)
        visited = visited | (
            jax.nn.one_hot(nxt, n_genes, dtype=jnp.bool_) & can_move[:, None])
        return (visited, nxt, can_move), None

    (visited, _, _), _ = jax.lax.scan(step, state0, uniforms)
    return visited


def random_walks(adj: jax.Array, starts: jax.Array, key: jax.Array,
                 len_path: int) -> jax.Array:
    """DEPRECATED dense walker shim — see :func:`_random_walks_dense`.

    The dense [G, G] walker is retired as a production path: it cannot
    reach the 262k+-gene scales the rest of the repo benches (the table
    alone is G^2 floats), and the production device sampler is now the
    bit-exact CSR walker in :mod:`g2vec_tpu.ops.device_walker` (same
    rows as the host C++ sampler, byte for byte). This shim keeps the
    dense kernel callable for small/test graphs but warns so no caller
    silently regresses to dense; new code should use
    ``device_walker.walk_packed_rows_device`` (production) or
    :func:`random_walks_sparse` (jax.random family, mesh-sharded
    tables).
    """
    import warnings

    warnings.warn(
        "ops.walker.random_walks (dense [G, G] adjacency) is deprecated: "
        "use ops.device_walker (bit-exact CSR device sampler) or "
        "random_walks_sparse (neighbor tables)", DeprecationWarning,
        stacklevel=2)
    return _random_walks_dense(adj, starts, key, len_path)


# Prefix-segmented no-revisit compare: at step s only slots 0..s of the
# [W, L] path buffer are filled, so comparing candidates against the FULL
# buffer wastes most of the dominant [W, D, L] compare on always-False
# slots. The scan is split into this many equal segments, each compiled
# with a static prefix bound (= the segment's last filled slot count) —
# total compare work drops to (K+1)/2K of the single-scan cost (0.625x at
# K=4) with bit-identical sampling (the dropped compares are against -1
# sentinels, which never match a candidate).
_SCAN_SEGMENTS = 4


def _sparse_path_scan(nbr_rows, starts: jax.Array, uniforms: jax.Array,
                      len_path: int,
                      n_segments: Optional[int] = None) -> jax.Array:
    """Shared sparse-walk scaffold; returns the [W, len_path] path lists.

    ``nbr_rows(current) -> (cand [W, D], w [W, D])`` gathers the current
    nodes' neighbor rows — the only piece that differs between the
    replicated and the model-sharded table layouts, so the two cannot drift
    semantically. -1 entries are empty path slots; the compare-based
    no-revisit test and the fixed trip count live only here.
    ``n_segments`` overrides _SCAN_SEGMENTS (profiling A/Bs; results are
    bit-identical for any value).
    """
    n_walkers = starts.shape[0]
    starts = starts.astype(jnp.int32)
    path0 = jnp.full((n_walkers, len_path), -1, dtype=jnp.int32)
    path0 = jax.lax.dynamic_update_slice(path0, starts[:, None], (0, 0))
    state0 = (path0, starts, jnp.ones((n_walkers,), dtype=jnp.bool_))

    def make_step(bound: int):
        def step(state, inputs):
            step_idx, u = inputs
            path_list, current, alive = state
            cand, w = nbr_rows(current)                    # [W, D] each
            # no revisit: a candidate equal to ANY node already on the path
            # is masked out. Fused broadcast-compare over the filled prefix
            # only — no [W, G] state, no gather (TPU has no per-lane
            # gather; compare-based membership is the idiomatic form).
            prefix = jax.lax.slice_in_dim(path_list, 0, bound, axis=1)
            seen = jnp.any(cand[:, :, None] == prefix[:, None, :], axis=2)
            w = jnp.where(seen, 0.0, w)                    # (+pads stay 0)
            slot, total = _sample_slots(w, u)
            nxt = _select_slot(cand, slot)
            w_sel = _select_slot(w, slot)
            can_move = alive & (total > 0.0) & (w_sel > 0.0)
            current = jnp.where(can_move, nxt, current)
            entry = jnp.where(can_move, nxt, -1)[:, None]  # -1 never matches
            path_list = jax.lax.dynamic_update_slice(
                path_list, entry, (0, step_idx + 1))
            return (path_list, current, can_move), None
        return step

    n_steps = uniforms.shape[0]
    # Equal segments; during steps [lo, hi) at most ``hi`` slots are
    # filled at compare time (step s compares slots 0..s, s <= hi-1).
    if n_segments is None:
        n_segments = _SCAN_SEGMENTS
    n_segments = min(n_segments, n_steps) or 1
    state = state0
    lo = 0
    for k in range(n_segments):
        hi = ((k + 1) * n_steps) // n_segments
        if hi <= lo:
            continue
        state, _ = jax.lax.scan(
            make_step(hi), state, (jnp.arange(lo, hi), uniforms[lo:hi]))
        lo = hi
    return state[0]


def _sparse_path_list(nbr_idx, nbr_w, starts, key, len_path: int,
                      n_segments: Optional[int] = None):
    """Replicated-table sparse walk -> [W, len_path] path lists.

    The single place that binds the uniform streams to the replicated
    neighbor-table layout; both public encodings (bool visited, packed
    bytes) consume it so they cannot drift.
    """
    n_steps = max(len_path - 1, 0)
    uniforms = _per_walker_uniforms(key, starts.shape[0], n_steps)

    def nbr_rows(current):
        return nbr_idx[current], nbr_w[current]

    return _sparse_path_scan(nbr_rows, starts, uniforms, len_path,
                             n_segments)


@partial(jax.jit, static_argnames=("len_path",))
def random_walks_sparse(nbr_idx: jax.Array, nbr_w: jax.Array,
                        starts: jax.Array, key: jax.Array,
                        len_path: int) -> jax.Array:
    """Sparse-transition twin of :func:`random_walks`.

    ``nbr_idx``/``nbr_w``: [G, D] padded out-neighbor lists from
    :func:`g2vec_tpu.ops.graph.neighbor_table` (padding = weight 0). Same
    walk semantics, but each step works on [W, D] instead of [W, G] and the
    step touches no [W, G] state at all (see module docstring). Returns
    visited [W, G] bool — identical encoding to the dense path.
    """
    path_list = _sparse_path_list(nbr_idx, nbr_w, starts, key, len_path)
    return _visited_from_path_list(path_list, nbr_idx.shape[0])


# --------------------------------------------------------------------------
# On-device bit-packing (np.packbits layout: MSB of byte 0 = gene 0).
# --------------------------------------------------------------------------

@jax.jit
def _packbits_rows(visited: jax.Array) -> jax.Array:
    """[W, G] bool -> [W, ceil(G/8)] uint8, matching np.packbits(axis=1)."""
    n = visited.shape[1]
    n_pad = (n + 7) // 8 * 8
    if n_pad != n:
        visited = jnp.pad(visited, ((0, 0), (0, n_pad - n)))
    bits = visited.reshape(visited.shape[0], n_pad // 8, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(7, -1, -1, dtype=jnp.uint8))
    return jnp.sum(bits * weights, axis=2, dtype=jnp.uint8)


_PACK_SLAB = 8   # path slots folded per pass in _packed_from_path_list


def _packed_from_path_list(path_list: jax.Array, n_genes: int) -> jax.Array:
    """[W, L] node lists (-1 = empty) -> packed [W, ceil(G/8)] uint8 directly.

    np.packbits layout without ever materializing the [W, G] bool mask
    (≈1 GB at a full bundled-scale launch): byte j of walker w ORs the bit
    of every path node whose gene id lives in byte j. Path nodes are unique
    (no-revisit), so the bits are distinct and a SUM equals the OR; -1
    sentinels contribute bit 0 and match no byte (arithmetic shift keeps
    them negative).

    The compare runs in slabs of _PACK_SLAB path slots (a fori_loop over
    L/8 passes): XLA is expected to fuse each [W, nb, 8] broadcast-compare
    straight into its reduce, but the slab bounds the worst case if it ever
    does not — a whole-L pass would be a [W, nb, L] intermediate (~10 GB at
    full bundled-launch scale), a slab is G-bytes-per-walker at most (and
    :func:`walker_working_set` budgets exactly that; a scatter-add would
    avoid the question but in-scan 2D scatters are the one construct that
    wedged XLA:TPU compilation outright, PROFILE.md).
    """
    nb = (n_genes + 7) // 8
    n_slots = path_list.shape[1]
    pad = (-n_slots) % _PACK_SLAB
    if pad:
        path_list = jnp.pad(path_list, ((0, 0), (0, pad)), constant_values=-1)
    byte_idx = path_list >> 3                              # [W, L']
    bit = jnp.where(path_list >= 0,
                    jnp.uint8(128) >> (path_list & 7).astype(jnp.uint8),
                    jnp.uint8(0))
    bytes_ax = jnp.arange(nb)[None, :, None]

    def body(k, acc):
        b_idx = jax.lax.dynamic_slice_in_dim(byte_idx, k * _PACK_SLAB,
                                             _PACK_SLAB, axis=1)
        b_bit = jax.lax.dynamic_slice_in_dim(bit, k * _PACK_SLAB,
                                             _PACK_SLAB, axis=1)
        match = b_idx[:, None, :] == bytes_ax              # [W, nb, SLAB]
        return acc + jnp.sum(
            jnp.where(match, b_bit[:, None, :], jnp.uint8(0)),
            axis=2, dtype=jnp.uint8)

    acc0 = jnp.zeros((path_list.shape[0], nb), dtype=jnp.uint8)
    return jax.lax.fori_loop(0, path_list.shape[1] // _PACK_SLAB, body, acc0)


@partial(jax.jit, static_argnames=("len_path",))
def _packed_walk_sparse(nbr_idx, nbr_w, starts, keys, len_path: int):
    """Sparse walk returning bit-packed rows, no [W, G] intermediate."""
    path_list = _sparse_path_list(nbr_idx, nbr_w, starts, keys, len_path)
    return _packed_from_path_list(path_list, nbr_idx.shape[0])


@partial(jax.jit, static_argnames=("len_path",))
def _packed_walk_dense(adj, starts, keys, len_path: int):
    visited = _random_walks_dense(adj, starts, keys, len_path)
    return _packbits_rows(visited)


# shard_map walk programs are built per (mesh, shapes) — cache them or every
# launch re-traces the whole scan (the jit cache keys on fn identity).
_SHARDED_WALK_CACHE: dict = {}


def _sharded_sparse_walk_fn(mesh, n_genes: int, len_path: int):
    """Sparse walk with the neighbor tables ROW-SHARDED over 'model'.

    Round-1 gap (VERDICT.md #9): under a mesh the 2*G*D tables were
    replicated per device, defeating the model axis at 40k+-gene scale.
    Here each model shard stores only its table rows; the per-step row
    gather becomes an ownership-masked local gather + psum over 'model'
    (each row has exactly one owner, so the sum reconstructs exactly
    ``nbr_idx[current]`` / ``nbr_w[current]`` in the same slot order — the
    uniforms, and therefore the sampled paths, are bit-identical to the
    unsharded walker for the same keys). Walkers stay DP over 'data';
    model shards duplicate the (cheap) per-walker sampling compute and
    carry identical path-list state. Returns bit-packed rows.
    """
    from jax.sharding import PartitionSpec as P

    from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map

    def walk(nbr_idx_local, nbr_w_local, starts, keys):
        rows_per_shard = nbr_idx_local.shape[0]
        base = jax.lax.axis_index(MODEL_AXIS) * rows_per_shard
        n_steps = max(len_path - 1, 0)
        uniforms = _per_walker_uniforms(keys, starts.shape[0], n_steps)

        def nbr_rows(current):
            local = current - base
            own = (local >= 0) & (local < rows_per_shard)
            safe = jnp.clip(local, 0, rows_per_shard - 1)
            cand = jnp.where(own[:, None], nbr_idx_local[safe], 0)
            w = jnp.where(own[:, None], nbr_w_local[safe], 0.0)
            return (jax.lax.psum(cand, MODEL_AXIS),
                    jax.lax.psum(w, MODEL_AXIS))

        path_list = _sparse_path_scan(nbr_rows, starts, uniforms, len_path)
        return _packed_from_path_list(path_list, n_genes)

    sharded = shard_map(
        walk, mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS, None),
                  P(DATA_AXIS), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS, None),
        # The scan carry mixes constants (alive mask init) with
        # data-varying state; the VMA check rejects that mix even though
        # the program is correct (same pattern as the trainer's
        # pallas-under-shard_map call).
        check_vma=False)
    return jax.jit(sharded)


# Replicating the neighbor tables is FASTER (zero collectives per step)
# whenever they fit comfortably: shard only past this per-device size, where
# the memory win pays for the two per-step [W, D] psums over 'model'.
SHARD_TABLE_BYTES = 128 * 1024 * 1024


def _get_sharded_walk_fn(mesh, n_genes: int, len_path: int):
    key = (mesh, n_genes, len_path)
    fn = _SHARDED_WALK_CACHE.get(key)
    if fn is None:
        fn = _sharded_sparse_walk_fn(mesh, n_genes, len_path)
        while len(_SHARDED_WALK_CACHE) >= 8:
            _SHARDED_WALK_CACHE.pop(next(iter(_SHARDED_WALK_CACHE)))
        _SHARDED_WALK_CACHE[key] = fn
    return fn


# --------------------------------------------------------------------------
# HBM working-set model: pick the walkers-per-launch automatically.
# --------------------------------------------------------------------------

# Default device-memory budget for one walk launch. A v5e chip has 16 GiB;
# 4 GiB leaves room for the transition tables, XLA scratch, and whatever
# else the pipeline keeps resident (the trainer's packed path matrix).
# Override per-run with walker_hbm_budget.
WALKER_HBM_BUDGET = 4 * 1024**3


def walker_working_set(n_genes: int, d_slots: int, len_path: int,
                       dense: bool) -> int:
    """Per-walker device bytes of one walk launch (model, not measurement).

    Sparse step: [D]-wide candidate/weight/cumsum temporaries (~4 f32/i32
    arrays live at once), the [L] int32 path list, [S] uniforms, and the
    packed-row encode (no [W, G] bool intermediate — the packed bytes come
    straight from the path list; budgeted at the WORST-case unfused
    [nb, _PACK_SLAB] compare slab plus accumulator/output, ~10 bytes per
    output byte, see _packed_from_path_list). Dense step: the [G]-wide row
    is the candidate buffer AND the visited row, and the bool mask is
    packed afterward.
    """
    if dense:
        per_step = 4 * 4 * n_genes           # adj row + masked + cumsum + sel
        encode = n_genes + (n_genes + 7) // 8   # visited bool + packed bits
    else:
        per_step = 4 * 4 * d_slots + 4 * len_path
        encode = (_PACK_SLAB + 2) * ((n_genes + 7) // 8)
    return per_step + 4 * max(len_path - 1, 1) + encode + 64


def auto_walker_batch(n_genes: int, d_slots: int, len_path: int,
                      n_walkers_total: int, dense: bool,
                      hbm_budget: int = 0) -> int:
    """Walkers per launch under ``hbm_budget`` (0 = WALKER_HBM_BUDGET).

    The budget governs the MARGINAL per-walker state only — transition
    tables are launch-invariant residents that batching cannot shrink
    (their lever is 'model'-axis sharding, SHARD_TABLE_BYTES), so they are
    deliberately outside this subtraction: dividing them out once drove
    the batch to 1 on a scale-free 45k-gene graph whose padded table
    alone exceeded the budget, turning one walk into 45k single-walker
    dispatches. Answers VERDICT r2 #4: the reference dies on dense [G, G]
    memory at 40k+ genes (ref: G2Vec.py:377) and round 2's walker made the
    batch a manual knob; this sizes it from a stated working-set model the
    same way the Pallas kernel sizes its tiles (ops/packed_matmul.py).
    """
    budget = hbm_budget if hbm_budget > 0 else WALKER_HBM_BUDGET
    per_walker = walker_working_set(n_genes, d_slots, len_path, dense)
    return int(max(1, min(n_walkers_total, budget // per_walker)))


def generate_path_set(adj, key: jax.Array, *, len_path: int, reps: int,
                      starts: Optional[np.ndarray] = None,
                      walker_batch: int = 0,
                      mesh_ctx=None,
                      shard_tables: Optional[bool] = None,
                      walker_hbm_budget: int = 0) -> Set[bytes]:
    """All-sources x reps walks -> set of packed multi-hot path rows.

    Mirrors generate_pathSet (G2Vec.py:324-352): every gene is a start node,
    ``reps`` times; results are set-deduplicated. Each element is the
    np.packbits encoding of the [G] bool row (fixed G; unpack with
    :func:`unpack_paths`), packed ON DEVICE — only G/8 bytes per walker
    cross the wire.

    ``adj`` is either a dense [G, G] transition matrix or a
    ``(nbr_idx [G, D], nbr_w [G, D])`` neighbor-table pair from
    :func:`g2vec_tpu.ops.graph.neighbor_table` — the sparse form is the
    TPU-efficient default for the pipeline (O(W*D) per step, no dense G^2
    HBM residency). All ``reps * len(starts)`` walkers flatten into ONE
    walker axis and launch in device batches of ``walker_batch`` (0 = sized
    by :func:`auto_walker_batch` against ``walker_hbm_budget``). The result
    is INVARIANT to the batch size: every walker's PRNG stream is keyed by
    its (repetition, global walker index), so the memory knob never changes
    which paths a given --seed produces. (It is NOT invariant to the
    dense/sparse choice — the two sample over differently shaped slot axes
    — but each is deterministic per seed.)

    ``mesh_ctx``: walkers are embarrassingly data-parallel — the walker axis
    shards over 'data'. Sparse tables additionally ROW-SHARD over 'model'
    when the mesh has one AND they are big enough to matter
    (``shard_tables``: None = auto at SHARD_TABLE_BYTES, or force with
    True/False). Each shard then stores 2*G*D/M values; the per-step gather
    becomes an ownership-masked local gather + psum that reconstructs the
    exact unsharded candidate rows (:func:`_sharded_sparse_walk_fn`), so
    the path set stays bit-identical. Small tables replicate — the walk
    compiles to zero collectives. Result-invariant vs single-device either
    way: shard padding walkers are dropped host-side and each walker's PRNG
    stream is its own.
    """
    from jax.sharding import PartitionSpec as P

    from g2vec_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, MeshContext,
                                         pad_to_multiple)

    sparse = isinstance(adj, tuple)
    ctx = mesh_ctx if mesh_ctx is not None else MeshContext(mesh=None)
    data_dim = 1 if ctx.mesh is None else ctx.mesh.shape[DATA_AXIS]
    model_dim = 1 if ctx.mesh is None else ctx.mesh.shape[MODEL_AXIS]
    walker_spec = P(DATA_AXIS)           # 1-D walker axis, rows over 'data'
    if sparse:
        nbr_idx, nbr_w = adj
        n_genes = int(nbr_idx.shape[0])
        d_slots = int(nbr_idx.shape[1])
        if shard_tables is None:
            # Auto: replicate small tables (collective-free walk); shard
            # once they are big enough that the memory win matters.
            shard_tables = (model_dim > 1
                            and nbr_idx.size * 8 > SHARD_TABLE_BYTES)
        if shard_tables and model_dim > 1:
            # Row-shard the tables over 'model' (zero-padded to split
            # evenly; pad rows are unreachable — nothing points at gene
            # ids >= n_genes, and their own weights are 0).
            g_pad = pad_to_multiple(n_genes, model_dim)
            nbr_idx = np.pad(np.asarray(nbr_idx),
                             ((0, g_pad - n_genes), (0, 0)))
            nbr_w = np.pad(np.asarray(nbr_w),
                           ((0, g_pad - n_genes), (0, 0)))
            table_spec = P(MODEL_AXIS, None)
        else:
            table_spec = P()
        table = (ctx.put(jnp.asarray(nbr_idx, dtype=jnp.int32), table_spec),
                 ctx.put(jnp.asarray(nbr_w, dtype=jnp.float32), table_spec))
    else:
        import warnings

        warnings.warn(
            "generate_path_set with a dense [G, G] adjacency is "
            "deprecated: pass a neighbor-table pair, or use "
            "ops.device_walker.generate_path_set_device (bit-exact CSR "
            "device sampler) — the dense table cannot reach production "
            "scales", DeprecationWarning, stacklevel=2)
        n_genes = int(adj.shape[0])
        d_slots = n_genes
        table = ctx.put(jnp.asarray(adj, dtype=jnp.float32), P())
    if starts is None:
        starts = np.arange(n_genes, dtype=np.int32)
    starts = np.asarray(starts, dtype=np.int32)

    # One flat walker axis over all repetitions. Stream identity: walker
    # (rep r, index i) draws from fold_in(split(key, reps)[r], i) — the
    # same derivation regardless of how launches slice the axis. Keys
    # travel as raw uint32 key DATA: numpy crosses host->global-sharding
    # fine, a committed typed-key array does not.
    rep_keys = jax.random.split(key, reps)
    all_keys = np.asarray(jax.random.key_data(jax.vmap(lambda rk: jax.vmap(
        lambda i: jax.random.fold_in(rk, i))(jnp.arange(starts.size))
    )(rep_keys)))
    all_keys = all_keys.reshape(reps * starts.size, -1)
    all_starts = np.tile(starts, reps)
    total = all_starts.size
    if walker_batch > 0:
        batch = walker_batch
    else:
        batch = auto_walker_batch(n_genes, d_slots, len_path, total,
                                  dense=not sparse,
                                  hbm_budget=walker_hbm_budget)

    # Every launch pads to the SAME [n_pad] walker shape (duplicate walker
    # 0, rows dropped after): one compiled program serves the whole run —
    # a ragged final chunk would otherwise recompile the scan.
    n_pad = pad_to_multiple(batch, data_dim)
    paths: Set[bytes] = set()
    for lo in range(0, total, batch):
        chunk = all_starts[lo:lo + batch]
        chunk_keys = all_keys[lo:lo + batch]
        n_real = chunk.size
        if n_pad != n_real:
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[:1], n_pad - n_real)])
            chunk_keys = np.concatenate(
                [chunk_keys,
                 np.repeat(chunk_keys[:1], n_pad - n_real, axis=0)])
        chunk = ctx.put(jnp.asarray(chunk), walker_spec)
        chunk_keys = ctx.put(chunk_keys, P(DATA_AXIS, None))
        if sparse and shard_tables and model_dim > 1:
            fn = _get_sharded_walk_fn(ctx.mesh, n_genes, len_path)
            packed_dev = fn(table[0], table[1], chunk, chunk_keys)
        elif sparse:
            packed_dev = _packed_walk_sparse(table[0], table[1], chunk,
                                             chunk_keys, len_path)
        else:
            packed_dev = _packed_walk_dense(table, chunk, chunk_keys,
                                            len_path)
        # fetch_global, not np.asarray: under a multi-process mesh the
        # packed rows span devices other processes own.
        from g2vec_tpu.parallel.distributed import fetch_global

        packed = np.asarray(fetch_global(packed_dev))[:n_real]
        paths.update(row.tobytes() for row in packed)
    return paths


def unpack_paths(packed: Sequence[bytes], n_genes: int) -> np.ndarray:
    """Packed path rows -> [N, n_genes] uint8 multi-hot (sorted for determinism).

    uint8, not int32: at reference scale (45k x 7.5k) the multi-hot matrix is
    ~340 MB this way; every consumer re-casts anyway (the trainer to its
    compute dtype, the frequency vote through numpy's promoting sum).
    """
    rows = _packed_rows(packed, n_genes)
    return np.unpackbits(rows, axis=1)[:, :n_genes]


def integrate_path_sets(path_set_good: Set[bytes], path_set_poor: Set[bytes],
                        n_genes: int, packed: bool = False,
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Drop paths common to both groups; return (multi-hot, labels).

    Reference: integrate_pathSet (G2Vec.py:310-322) — a path gene-set present
    in BOTH groups' sets carries no prognosis signal and is removed from
    both; survivors get their group index as the label. The reference's
    trailing label column is a separate array here (the trainer takes
    (paths, labels), not a glued matrix). Row order: good block then poor
    block, each sorted by packed bytes (the reference iterates Python-set
    order — nondeterministic; we pin it).

    ``packed=True`` returns the paths still bit-packed ([N, ceil(G/8)]
    uint8, np.packbits layout) — the scalable form the pipeline feeds
    straight to the trainer: the dense uint8 [N, G] matrix is never
    materialized on host (8x smaller at any scale).
    """
    common = path_set_good & path_set_poor
    fn = _packed_rows if packed else unpack_paths
    good = fn(path_set_good - common, n_genes)
    poor = fn(path_set_poor - common, n_genes)
    paths = np.concatenate([good, poor], axis=0)
    labels = np.concatenate([
        np.zeros(good.shape[0], dtype=np.int32),
        np.ones(poor.shape[0], dtype=np.int32)])
    return paths, labels


def _packed_rows(packed: Set[bytes], n_genes: int) -> np.ndarray:
    """Set of packed rows -> [N, ceil(G/8)] uint8 (sorted for determinism)."""
    nb = (n_genes + 7) // 8
    if not packed:
        return np.zeros((0, nb), dtype=np.uint8)
    rows = np.frombuffer(b"".join(sorted(packed)), dtype=np.uint8)
    return rows.reshape(len(packed), nb)


def count_gene_freq(paths: np.ndarray, labels: np.ndarray,
                    genes: Sequence[str], packed: bool = False,
                    ) -> Dict[str, int]:
    """Per-gene majority vote over the integrated path set.

    Reference: count_geneFreq (G2Vec.py:288-308) — for each gene appearing in
    at least one path, count good vs poor paths containing it; majority ->
    0/1, tie -> 2. Genes in no path are absent from the dict (callers default
    them to 2, ref: G2Vec.py:172).

    With ``packed=True``, ``paths`` is the bit-packed [N, ceil(G/8)] uint8
    form (integrate_path_sets(packed=True)); rows are expanded in bounded
    chunks so the dense matrix never materializes whole.
    """
    n_genes = len(genes)
    if packed:
        if paths.shape[1] != (n_genes + 7) // 8:
            raise ValueError(
                f"packed paths width {paths.shape[1]} inconsistent with "
                f"{n_genes} genes (expected {(n_genes + 7) // 8})")

        def colsum(block):
            total = np.zeros(n_genes, dtype=np.int64)
            for lo in range(0, block.shape[0], 4096):
                rows = np.unpackbits(block[lo:lo + 4096], axis=1)[:, :n_genes]
                total += rows.sum(axis=0, dtype=np.int64)
            return total

        good_counts = colsum(paths[labels == 0])
        poor_counts = colsum(paths[labels == 1])
    else:
        good_counts = paths[labels == 0].sum(axis=0)
        poor_counts = paths[labels == 1].sum(axis=0)
    result: Dict[str, int] = {}
    for i, g in enumerate(genes):
        fg, fp = int(good_counts[i]), int(poor_counts[i])
        if fg == 0 and fp == 0:
            continue
        result[g] = 0 if fg > fp else (1 if fg < fp else 2)
    return result
