"""L3/L5 device ops: PCC adjacency, random walks, statistics, k-means.

Everything here is jit-compiled JAX operating on device-resident arrays;
host-side glue (dedup, dict building, sorting by gene symbol) lives in
:mod:`g2vec_tpu.analysis` and :mod:`g2vec_tpu.ops.paths`.
"""
