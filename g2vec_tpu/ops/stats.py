"""L5 statistics ops — jitted, vectorized over the gene axis.

The reference computes these one gene at a time in Python loops
(compute_tscores, G2Vec.py:151-157; compute_tstatistics, G2Vec.py:138-149;
transform_minmax, G2Vec.py:133-136). Here each is one fused XLA kernel over
the whole gene axis.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=())
def tscores(expr_good: jax.Array, expr_poor: jax.Array) -> jax.Array:
    """|pooled-variance two-sample t| per gene (ref: G2Vec.py:138-157).

    ``expr_good``: [n0, G] expression of label-0 samples; ``expr_poor``:
    [n1, G] of label-1 samples. Matches the reference exactly:

    - sample std with ddof=1 (G2Vec.py:140)
    - pooled denominator sqrt(((n0-1)s0^2 + (n1-1)s1^2) / (n0+n1-2))
      times sqrt(1/n0 + 1/n1) (G2Vec.py:143-144)
    - 0.0 whenever either denominator is not strictly positive
      (G2Vec.py:145-148), which also covers the constant-gene case
    - absolute value taken by the caller loop in the reference
      (G2Vec.py:156); taken here directly.

    Note the reference's argument names ("n_poor" for the label-0 group) are
    misleading; the formula is symmetric up to sign, and abs() is applied.
    """
    n0 = expr_good.shape[0]
    n1 = expr_poor.shape[0]
    m0 = expr_good.mean(axis=0)
    m1 = expr_poor.mean(axis=0)
    s0 = expr_good.std(axis=0, ddof=1)
    s1 = expr_poor.std(axis=0, ddof=1)
    pooled = ((n0 - 1.0) * s0 * s0 + (n1 - 1.0) * s1 * s1) / (n0 + n1 - 2.0)
    d1 = jnp.sqrt(pooled)
    d2 = jnp.sqrt(1.0 / n0 + 1.0 / n1)
    ok = (d1 > 0.0) & (d2 > 0.0)
    t = jnp.where(ok, (m0 - m1) / jnp.where(ok, d1, 1.0) / d2, 0.0)
    return jnp.abs(t)


@jax.jit
def minmax(scores: jax.Array, new_min: float = 0.0, new_max: float = 1.0) -> jax.Array:
    """Linear rescale to [new_min, new_max] (ref: G2Vec.py:133-136).

    Guarded: a constant score vector maps to all-new_min instead of the
    reference's division by zero (SURVEY.md §7 quirk (f))."""
    old_min = scores.min()
    old_max = scores.max()
    span = old_max - old_min
    safe = jnp.where(span > 0.0, span, 1.0)
    return jnp.where(span > 0.0,
                     (new_max - new_min) / safe * (scores - old_min) + new_min,
                     jnp.full_like(scores, new_min))


@jax.jit
def masked_minmax(scores: jax.Array, mask: jax.Array,
                  new_min: float = 0.0, new_max: float = 1.0) -> jax.Array:
    """:func:`minmax` over the ``mask``-selected subset, WITHOUT gathering.

    The device-resident stage-6 path (analysis.py) scores each L-group as
    a masked view of the full gene axis instead of bouncing through a
    host-side boolean gather: min/max are order-independent and exact, so
    the masked reduction sees exactly the gathered subset's extrema, and
    the rescale below is the same per-element expression :func:`minmax`
    applies — masked positions therefore carry bitwise the values the
    gathered call produced (pinned by the byte-golden e2e fixtures).
    Unmasked positions are rescaled garbage the caller must never read;
    an all-False mask or a constant subset degrades to all-new_min, the
    same guard as :func:`minmax`.
    """
    old_min = jnp.min(jnp.where(mask, scores, jnp.inf))
    old_max = jnp.max(jnp.where(mask, scores, -jnp.inf))
    span = old_max - old_min
    safe = jnp.where(span > 0.0, span, 1.0)
    return jnp.where(span > 0.0,
                     (new_max - new_min) / safe * (scores - old_min) + new_min,
                     jnp.full_like(scores, new_min))


@jax.jit
def dscores(embeddings: jax.Array) -> jax.Array:
    """Row-wise L2 norm of embedding rows (ref: G2Vec.py:96)."""
    return jnp.sqrt(jnp.sum(embeddings * embeddings, axis=1))


# ---------------------------------------------------------------------------
# Split masked min-max (ROADMAP item 2 — gene-range-sharded stage 6)
# ---------------------------------------------------------------------------
# masked_minmax factored into its two halves so a rank holding only a
# [G/ranks] slice can compute LOCAL masked extrema, allreduce the two
# scalars (min/max are order-independent, so the reduced values are
# bitwise the global call's), and apply the identical rescale expression
# locally. masked_minmax itself is golden-pinned and stays untouched;
# these mirror its arithmetic term for term.

@jax.jit
def masked_extrema(scores: jax.Array, mask: jax.Array):
    """(min, max) over the masked subset — +inf/-inf when the local mask
    is empty, the identities the cross-rank min/max reduction needs."""
    return (jnp.min(jnp.where(mask, scores, jnp.inf)),
            jnp.max(jnp.where(mask, scores, -jnp.inf)))


@jax.jit
def masked_rescale(scores: jax.Array, old_min: jax.Array,
                   old_max: jax.Array, new_min: float = 0.0,
                   new_max: float = 1.0) -> jax.Array:
    """:func:`masked_minmax`'s rescale half with the extrema supplied by
    the caller. ``masked_rescale(s, *masked_extrema(s, m))`` is bitwise
    ``masked_minmax(s, m)`` (same expression, same guard); with globally
    reduced extrema the masked positions of every rank's slice carry
    exactly the values the unsharded call would produce."""
    span = old_max - old_min
    safe = jnp.where(span > 0.0, span, 1.0)
    return jnp.where(span > 0.0,
                     (new_max - new_min) / safe * (scores - old_min) + new_min,
                     jnp.full_like(scores, new_min))
