"""L5 — IVF approximate-NN index over one bundle's ``[G, H]`` rows.

The exact query kernel (ops/knn.py) is O(G) per query; past ~1M genes
that arithmetic alone blows the warm-p99 budget BENCH_QUERY.json pins.
This module trades a bounded recall loss for an O(G/nlist * nprobe)
candidate scan: rows are coarse-quantized against ``nlist`` centroids
(inverted-file layout — one posting array of row ids grouped by list,
plus a ``[nlist+1]`` offsets table), a query probes the ``nprobe``
nearest lists, and the survivors are EXACT-rescored with the same
blocked cosine arithmetic as the exact path. Whenever the true top-k
rows live in the probed lists the answer is float-exact — bitwise —
to ops/knn.cosine_topk; the recall@k >= 0.95 contract at pruning
scale is pinned in tests/test_ann.py.

Deliberately HOST-SIDE numpy and jax-free at module level: the index
is built once at bundle-publication time and queried through
serve/inventory.py, which the router (a jax-free module per
analyze/purity.py) imports for its failover read path. Centroid
refinement is therefore a numpy mirror of ops/kmeans's Lloyd step —
including its pinned empty-cluster contract (an empty cluster keeps
its previous center VERBATIM; parity with ops.kmeans._update_centers
is itself a test) — seeded either from the stage-5 k-means centroids
(free, when shapes permit) or from evenly-spaced rows.

Determinism contract (pinned): the build uses NO RNG — normalization,
evenly-spaced seeding, fixed-iteration Lloyd, and stable sorts only —
so the same embedding bytes + (nlist, seed centroids) always produce
the same index bytes, keyed like the walk cache.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from g2vec_tpu.ops import knn

#: Index file set, published next to the exact arrays and sha256'd
#: into the bundle's MANIFEST.json like every other file.
ANN_FILES = ("ann_centroids.npy", "ann_postings.npy", "ann_offsets.npy")
#: Wire/disk format tag recorded in meta.json["ann"]["format"].
ANN_FORMAT = "g2vec-ivf-v1"
#: ``resolve_nlist(n, 0)`` (auto) only indexes bundles with at least
#: this many rows — below it the exact kernel is already microseconds
#: and an index would be pure publication overhead.
ANN_AUTO_MIN_ROWS = 4096
#: Default probe width when a query does not pass ``nprobe``.
DEFAULT_NPROBE = 8
#: Fixed Lloyd refinement budget — data-independent iteration count,
#: same design choice as ops/kmeans (no tolerance check).
LLOYD_ITERS = 10


def resolve_nlist(n_rows: int, ann_nlist: int = 0) -> int:
    """Effective list count for a bundle of ``n_rows`` rows.

    ``ann_nlist < 0`` disables indexing; ``> 0`` is an explicit count
    (clamped to ``n_rows`` — more lists than rows is meaningless);
    ``0`` (auto) picks ``round(sqrt(n_rows))`` — the classic IVF
    balance point where probe cost and list-scan cost match — but only
    once ``n_rows >= ANN_AUTO_MIN_ROWS``. Returns 0 for "no index".
    """
    n_rows = int(n_rows)
    ann_nlist = int(ann_nlist)
    if ann_nlist < 0 or n_rows <= 0:
        return 0
    if ann_nlist > 0:
        return min(ann_nlist, n_rows)
    if n_rows < ANN_AUTO_MIN_ROWS:
        return 0
    return min(int(round(math.sqrt(n_rows))), n_rows)


def _normalize_rows(x: np.ndarray) -> np.ndarray:
    """Unit-normalize rows; zero-norm rows become zero vectors (they
    score -2.0 in the cosine kernel and may land in any list)."""
    x = np.asarray(x, dtype=np.float32)
    n = np.sqrt(np.einsum("ij,ij->i", x, x))
    ok = n > 0
    return np.where(ok[:, None], x / np.where(ok, n, 1)[:, None], x)


def lloyd_update(x: np.ndarray, centers: np.ndarray,
                 assign: np.ndarray) -> np.ndarray:
    """One numpy Lloyd center update, mirroring the pinned contract of
    ``ops.kmeans._update_centers``: a cluster with no members keeps its
    previous center VERBATIM (no respawn, no perturbation). Grouping is
    a stable argsort + ``np.add.reduceat`` — vectorized and
    deterministic (no float-order ambiguity: rows are summed in
    ascending row order within each cluster)."""
    nlist = centers.shape[0]
    counts = np.bincount(assign, minlength=nlist).astype(np.int64)
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(counts)[:-1]])
    sums = np.zeros_like(centers, dtype=np.float64)
    nonempty = counts > 0
    if order.size:
        # reduceat needs strictly valid start offsets; rows of empty
        # clusters would alias the next cluster's first row, so reduce
        # over non-empty clusters only and scatter back.
        red = np.add.reduceat(x[order].astype(np.float64),
                              starts[nonempty], axis=0)
        sums[nonempty] = red
    out = centers.astype(np.float64, copy=True)
    out[nonempty] = sums[nonempty] / counts[nonempty, None]
    return out.astype(np.float32)


def _assign(xb: np.ndarray, centers: np.ndarray,
            block_rows: int = 65536) -> np.ndarray:
    """Nearest-center assignment under squared euclidean in the
    normalized space, blocked so a memory-mapped ``[G, H]`` table
    never materializes at once. ``||x-c||^2 = ||x||^2 + ||c||^2 -
    2 x.c`` and ``||x||^2`` is constant per row, so the argmin is over
    ``||c||^2 - 2 x.c``; argmin ties resolve to the lowest list index
    (numpy's contract), same as the jax path."""
    g = xb.shape[0]
    c2 = np.einsum("ij,ij->i", centers, centers)
    out = np.empty(g, dtype=np.int64)
    for lo in range(0, g, block_rows):
        hi = min(g, lo + block_rows)
        dots = xb[lo:hi] @ centers.T
        out[lo:hi] = np.argmin(c2[None, :] - 2.0 * dots, axis=1)
    return out


def build_ivf(embeddings: np.ndarray, nlist: int,
              seed_centroids: Optional[np.ndarray] = None,
              iters: int = LLOYD_ITERS
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build the index: ``(centroids f32 [nlist, H], postings i32 [G],
    offsets i64 [nlist+1])``.

    Clustering runs in row-normalized space (cosine retrieval), seeded
    from the stage-5 k-means ``seed_centroids`` when their trailing dim
    matches ``H`` (normalized, first ``nlist`` rows), topped up with
    evenly-spaced normalized embedding rows; then ``iters`` fixed Lloyd
    updates. Deterministic end to end — no RNG anywhere.
    """
    embeddings = np.asarray(embeddings)
    if embeddings.ndim != 2 or embeddings.shape[0] < 1:
        raise ValueError(f"build_ivf needs a non-empty [G, H] matrix, "
                         f"got shape {embeddings.shape}")
    g, h = embeddings.shape
    nlist = int(nlist)
    if not (1 <= nlist <= g):
        raise ValueError(f"build_ivf needs 1 <= nlist <= {g}, "
                         f"got {nlist}")
    xb = _normalize_rows(embeddings)
    seeds = []
    if seed_centroids is not None:
        sc = np.asarray(seed_centroids, dtype=np.float32)
        if sc.ndim == 2 and sc.shape[1] == h and sc.shape[0] >= 1:
            seeds.append(_normalize_rows(sc)[:nlist])
    have = seeds[0].shape[0] if seeds else 0
    nfill = nlist - have
    if nfill > 0:
        fill_idx = (np.arange(nfill, dtype=np.int64) * g) // nfill
        seeds.append(xb[fill_idx])
    centers = np.concatenate(seeds, axis=0) if len(seeds) > 1 \
        else seeds[0]
    for _ in range(int(iters)):
        centers = lloyd_update(xb, centers, _assign(xb, centers))
    assign = _assign(xb, centers)
    counts = np.bincount(assign, minlength=nlist).astype(np.int64)
    # Stable argsort: postings are ascending row id WITHIN each list —
    # the order cosine_topk_subset's tie rule depends on.
    postings = np.argsort(assign, kind="stable").astype(np.int32)
    offsets = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.cumsum(counts)])
    return centers.astype(np.float32), postings, offsets


class IVFIndex:
    """One mapped index: centroids + postings + offsets, with shape
    sanity enforced at construction so a structurally-broken index is
    refused before it can ever mis-answer a query."""

    def __init__(self, centroids: np.ndarray, postings: np.ndarray,
                 offsets: np.ndarray, n_rows: int, hidden: int,
                 pvecs: Optional[np.ndarray] = None):
        centroids = np.asarray(centroids)
        postings = np.asarray(postings)
        offsets = np.asarray(offsets)
        if centroids.ndim != 2 or centroids.shape[1] != int(hidden) \
                or centroids.shape[0] < 1:
            raise ValueError(f"ann centroids {centroids.shape} vs "
                             f"hidden={hidden}")
        nlist = centroids.shape[0]
        if offsets.ndim != 1 or offsets.shape[0] != nlist + 1:
            raise ValueError(f"ann offsets {offsets.shape} vs "
                             f"nlist={nlist}")
        if postings.ndim != 1 or postings.shape[0] != int(n_rows):
            raise ValueError(f"ann postings {postings.shape} vs "
                             f"G={n_rows}")
        off = offsets.astype(np.int64)
        if off[0] != 0 or off[-1] != int(n_rows) or \
                np.any(np.diff(off) < 0):
            raise ValueError("ann offsets not a monotone [0..G] table")
        if postings.shape[0] and (postings.min() < 0
                                  or postings.max() >= int(n_rows)):
            raise ValueError("ann postings reference rows outside "
                             f"[0, {n_rows})")
        self.centroids = np.asarray(centroids, dtype=np.float32)
        self.postings = postings
        self.offsets = off
        self.nlist = nlist
        self.n_rows = int(n_rows)
        if pvecs is not None:
            pvecs = np.asarray(pvecs)
            if pvecs.ndim != 2 or pvecs.shape[0] != int(n_rows) \
                    or pvecs.shape[1] != int(hidden):
                raise ValueError(f"ann posting-major vectors "
                                 f"{pvecs.shape} vs [{n_rows}, {hidden}]")
        self.pvecs = pvecs

    def probe_lists(self, q: np.ndarray, nprobe: int) -> np.ndarray:
        """Ascending-sorted ids of the ``nprobe`` nearest lists —
        nearest under the SAME metric the build assigned rows with
        (squared euclidean against the normalized query), so a row
        always probes its own list first when the query sits on it."""
        nprobe = min(max(int(nprobe), 1), self.nlist)
        q = np.asarray(q, dtype=np.float32).reshape(-1)
        qn = np.sqrt(np.dot(q, q))
        if qn > 0:
            q = q / qn
        c2 = np.einsum("ij,ij->i", self.centroids, self.centroids)
        scores = c2 - 2.0 * (self.centroids @ q)
        if nprobe < self.nlist:
            lists = np.argpartition(scores, nprobe - 1)[:nprobe]
        else:
            lists = np.arange(self.nlist)
        return np.sort(lists)

    def probe(self, q: np.ndarray, nprobe: int) -> np.ndarray:
        """Sorted (ascending, duplicate-free) candidate row ids from
        the ``nprobe`` nearest lists (:meth:`probe_lists`)."""
        parts = [np.asarray(
            self.postings[self.offsets[li]:self.offsets[li + 1]])
            for li in self.probe_lists(q, nprobe)]
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.sort(np.concatenate(parts).astype(np.int64))


def posting_major_topk(norms: np.ndarray, index: IVFIndex, q: np.ndarray,
                       k: int, nprobe: int = DEFAULT_NPROBE,
                       exclude: int = -1, block_rows: int = 8192
                       ) -> "Tuple[np.ndarray, np.ndarray, int]":
    """The streaming twin of probe + :func:`knn.cosine_topk_subset`:
    candidate vectors come from the index's posting-major copy
    (``index.pvecs``), so each probed list is ONE contiguous slab read
    instead of a per-row fancy-indexed gather over the ``[G, H]`` map.

    Bitwise-equality contract (pinned by tests/test_ann.py): slab
    reads only assemble the candidate ARENA — the dots themselves run
    over the arena reordered to ascending global row id, in the SAME
    ``block_rows`` blocks as :func:`knn.cosine_topk_subset`, followed
    by the same ``np.where`` zero-norm guard against ``norms[row] *
    qn``, the same ``-inf`` exclude, and the same ``_topk_desc``
    select. Matching the GEMV block shapes is load-bearing, not
    cosmetic: BLAS dispatches different accumulation kernels by
    operand shape, so the same float32 row dotted inside a 39-row
    slab and inside an 8192-row block can differ in the last ulp.
    Scoring per-list slabs directly would therefore break bitwise
    equality at scale even though every row value is byte-identical.
    """
    lists = index.probe_lists(q, nprobe)
    q32 = np.asarray(q, dtype=np.float32).reshape(-1)
    qn = np.sqrt(np.dot(q32, q32))
    id_parts, vec_parts = [], []
    for li in lists:
        o0, o1 = index.offsets[li], index.offsets[li + 1]
        if o1 <= o0:
            continue
        id_parts.append(np.asarray(index.postings[o0:o1],
                                   dtype=np.int64))
        vec_parts.append(np.asarray(index.pvecs[o0:o1],
                                    dtype=np.float32))
    if not id_parts:
        return (np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.float32), 0)
    ids = np.concatenate(id_parts)
    # Rows live in exactly one list, so ids are unique; sorting them
    # ascending makes position order == global row id order, the
    # precondition for _topk_desc's tie rule matching the exact path.
    order = np.argsort(ids, kind="stable")
    ids = ids[order]
    vecs = np.concatenate(vec_parts)[order]
    m = ids.shape[0]
    sims = np.empty(m, dtype=np.float32)
    for lo in range(0, m, block_rows):
        hi = min(m, lo + block_rows)
        sims[lo:hi] = vecs[lo:hi] @ q32
    denom = np.asarray(norms, dtype=np.float32)[ids] * qn
    ok = denom > 0
    sims = np.where(ok, sims / np.where(ok, denom, 1), np.float32(-2.0))
    if 0 <= exclude < index.n_rows:
        pos = np.searchsorted(ids, exclude)
        if pos < ids.shape[0] and ids[pos] == exclude:
            sims[pos] = -np.inf
    loc = knn._topk_desc(sims, k)
    return ids[loc], sims[loc], int(ids.size)


def ivf_topk(emb: np.ndarray, norms: np.ndarray, index: IVFIndex,
             q: np.ndarray, k: int, nprobe: int = DEFAULT_NPROBE,
             exclude: int = -1, block_rows: int = 8192,
             posting_major: Optional[bool] = None
             ) -> "Tuple[np.ndarray, np.ndarray, int]":
    """Approximate cosine top-k: probe, then exact-rescore survivors.

    Returns ``(idx, sims, n_candidates)``. When the probe covers every
    row (``nprobe >= nlist``, or every populated list probed) the call
    delegates to :func:`ops.knn.cosine_topk` outright, so the
    degenerate case is STRUCTURALLY bitwise-equal to the exact path,
    not merely numerically close.

    ``posting_major`` selects the candidate storage: ``None`` (auto)
    streams the contiguous posting-ordered copy whenever the index
    carries one (:func:`posting_major_topk` — bitwise-equal answers),
    ``False`` forces the row-gather path (the bench A/B control),
    ``True`` requires the copy and raises without it.
    """
    g = emb.shape[0]
    use_pm = (index.pvecs is not None) if posting_major is None \
        else bool(posting_major)
    if use_pm and index.pvecs is None:
        raise ValueError("posting_major=True but the index carries no "
                         "posting-major vector copy")
    if use_pm:
        nprobe_eff = min(max(int(nprobe), 1), index.nlist)
        if nprobe_eff >= index.nlist:
            idx, sims = knn.cosine_topk(emb, norms, q, k,
                                        exclude=exclude,
                                        block_rows=block_rows)
            return idx, sims, g
        return posting_major_topk(norms, index, q, k, nprobe=nprobe,
                                  exclude=exclude, block_rows=block_rows)
    cand = index.probe(q, nprobe)
    if cand.size >= g:
        idx, sims = knn.cosine_topk(emb, norms, q, k, exclude=exclude,
                                    block_rows=block_rows)
        return idx, sims, g
    if cand.size == 0:
        return (np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.float32), 0)
    idx, sims = knn.cosine_topk_subset(emb, norms, cand, q, k,
                                       exclude=exclude,
                                       block_rows=block_rows)
    return idx, sims, int(cand.size)
