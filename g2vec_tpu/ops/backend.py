"""Walker-backend resolution: host-walks, chip-trains by default.

Stage 3 is the reference's self-declared hottest stage ("most time
consuming step", ref: G2Vec.py:58). This framework has two samplers with
one output contract (packed multi-hot rows):

- ``native`` — the threaded C++ CSR sampler (native/walker.cpp via
  ops/host_walker.py), O(out_degree + path_len) per step on host cores;
- ``device`` — the JAX lockstep walker (ops/walker.py), vectorized over
  all walkers on the accelerator, and the only one that shards its
  neighbor tables over a mesh.

Measured division of labor (PROFILE.md cross-backend table, at the
bundled example's scale — 9.9k genes, ~99k walks/group, lenPath=80; each
rate is paired with the reference-loop baseline measured in the SAME run
on the same host):

    native C++ sampler (r4, in-loop packing) ~98,100 walks/s (~426x ref loop)
    native C++ sampler (r3, numpy re-pack)   ~63,600 walks/s (~390x ref loop)
    device walker on a v5e chip               >6,100 walks/s (stage bound)
    device walker on XLA:CPU                    ~180 walks/s
    reference's per-node Python loop        ~163-230 walks/s (host-dependent)

The walk step is a pointer-chase through a weighted adjacency — branchy,
byte-sized state, no matmul anywhere — which is CPU-shaped work, while
the trainer's fused packed-matmul epochs are MXU-shaped work. So
``auto`` (the config default) routes walks to the host sampler whenever
it is available, and keeps the accelerator for training: each backend
stays deterministic per seed within its own PRNG family
(ops/host_walker.py docstring has the cross-backend caveat). A meshed
run changes nothing (walks are upstream of the sharded trainer); a
multi-process run shards the walker axis across hosts and allgathers
the packed rows (parallel/distributed.sharded_native_path_set —
bit-identical to the single-host result), provided EVERY process can
build the sampler (agreement-checked collectively; any host missing the
toolchain resolves the whole job to the device walker). The device
walker remains the explicit-pin path for graphs whose tables want to
live sharded on the accelerators (ops/walker.py row-shards them
bit-identically over the mesh).
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from g2vec_tpu.config import G2VecConfig


def native_walker_available() -> bool:
    """True when the C++ sampler can be built/loaded on this host.

    First call may pay a one-time ~1s g++ compile (memoized either way by
    native/_build.py, so this is cheap to call repeatedly).
    """
    try:
        from g2vec_tpu.native.walker_bindings import load

        load()
        return True
    except RuntimeError:
        return False


def resolve_walker_backend(cfg: "G2VecConfig") -> str:
    """Map ``cfg.walker_backend`` ("auto"|"device"|"native") to a concrete
    backend for this run. Explicit choices are honored as-is ("native" on
    a host without a toolchain stays "native" and raises at use with the
    actionable build error rather than silently changing PRNG families).

    In a multi-process run this is a COLLECTIVE for "auto" (all processes
    must agree on one backend, and the availability allgather is itself a
    synchronization point); every process calls it at the same place in
    the pipeline.
    """
    if cfg.walker_backend != "auto":
        return cfg.walker_backend
    avail = native_walker_available()
    if cfg.distributed:
        import jax

        if jax.process_count() > 1:
            import numpy as np

            # Backend-aware transport (KV on CPU fleets, watchdogged XLA
            # elsewhere) — a dead peer names itself instead of wedging.
            from g2vec_tpu.parallel.distributed import host_allgather

            flags = host_allgather("walker_backend",
                                   np.array([avail], dtype=bool))
            avail = bool(flags.all())
    return "native" if avail else "device"
