"""Native-CPU path generation — the host fallback twin of ops/walker.py.

SURVEY.md §2 names two optional native components for this framework; the
C++ TSV reader is one, this sampler is the other: on a host with no
accelerator the JAX lockstep walker pays XLA-on-CPU overheads it was never
designed for, while the reference's own per-node loop costs O(G) per step
(the dense-row deepcopy at ref: G2Vec.py:334). The native sampler walks
CSR rows at O(out_degree + path_len) per step across OS threads
(native/walker.cpp). It is the measured DEFAULT on every host, chip
attached or not: the walk step is branchy pointer-chasing with no matmul,
so even the real v5e device walker stays an order of magnitude behind
(~98k native vs >6.1k device walks/s — the measured table in
ops/backend.py); the device walker's remaining role is mesh-sharded
neighbor tables.

Same output contract as :func:`g2vec_tpu.ops.walker.generate_path_set`:
a set of np.packbits-encoded multi-hot rows over the sorted gene order —
dedup and the downstream integrate/count/train stages cannot tell the
backends apart. Same walk SEMANTICS (no revisit, weight-proportional
sampling, dead-end stop, every gene a start node reps times,
ref: G2Vec.py:324-352); per-seed deterministic for any thread count
(streams are keyed by (seed, repetition, start-index) within this
backend's own counter-based PRNG family).

PARITY ORACLE: this sampler's splitmix64 streams and walk-step contract
are now shared verbatim by the production device sampler
(:mod:`g2vec_tpu.ops.device_walker`) — device packed rows are
BYTE-IDENTICAL to this module's for the same (CSR bytes, walk params,
seed), including mid-walk :class:`WalkStateBatch` suspend/resume, and
the tier-1 parity battery pins host-vs-device word-for-word
(tests/test_device_walker.py). The legacy jax.random lockstep walker in
ops/walker.py remains the one differently-seeded family (the documented
DEVICE_FAMILY caveat in cache.py).
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Optional, Set

import numpy as np

# ---- the sampler thread pool ------------------------------------------------
# The walker axis is sharded in PYTHON (contiguous ranges over a persistent
# ThreadPoolExecutor; each range calls the C++ sampler single-threaded with
# the GIL released by ctypes) rather than inside one C++ call: the pool is
# shared by BOTH prognosis groups, so the overlap scheduler
# (parallel/overlap.py) can sample group 2 while group 1 is still draining
# — ranges from the two groups interleave on the same cores instead of the
# second group waiting for a full-width C++ join. Bit-identity at any
# thread count is structural: streams are keyed by global walker index and
# every range writes a fixed disjoint row slice of one output buffer.
# The pool is private to this module — overlap.py uses its own executor;
# sharing one would let a stage task that WAITS on range futures starve
# the ranges it waits for.
_POOL: Optional[ThreadPoolExecutor] = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()

#: Walkers per pool task. Small enough that two concurrent groups
#: interleave at sub-second granularity, large enough that the per-task
#: dispatch overhead (a ctypes call) stays negligible.
RANGE_CHUNK = 2048


def resolve_sampler_threads(n_threads: int = 0) -> int:
    """Map the --sampler-threads value to a concrete count: 0 (auto) means
    every core (``G2VEC_SAMPLER_THREADS`` overrides — the bench and tests
    pin counts through it without plumbing flags)."""
    if n_threads < 0:
        raise ValueError(f"sampler threads must be >= 0, got {n_threads}")
    if n_threads:
        return n_threads
    env = os.environ.get("G2VEC_SAMPLER_THREADS")
    if env:
        try:
            n = int(env)
        except ValueError as e:
            raise ValueError(
                f"G2VEC_SAMPLER_THREADS must be an int, got {env!r}") from e
        if n > 0:
            return n
    return max(1, os.cpu_count() or 1)


def _pool(n_threads: int) -> ThreadPoolExecutor:
    """The shared sampler pool, grown (never shrunk) to ``n_threads``."""
    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE < n_threads:
            old = _POOL
            _POOL = ThreadPoolExecutor(
                max_workers=n_threads, thread_name_prefix="g2v-sampler")
            _POOL_SIZE = n_threads
            if old is not None:
                old.shutdown(wait=False)
        return _POOL


def edges_to_csr(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                 n_genes: int):
    """(src, dst, w) edge lists -> CSR (indptr [G+1], indices [E], w [E]).

    Directed, duplicate edges kept — identical multiset semantics to the
    padded neighbor_table (ops/graph.py), just without the max-degree
    padding that a CPU scan does not need.
    """
    order = np.argsort(src, kind="stable")
    indices = np.ascontiguousarray(dst[order], dtype=np.int32)
    weights = np.ascontiguousarray(w[order], dtype=np.float32)
    counts = np.bincount(src, minlength=n_genes)
    indptr = np.zeros(n_genes + 1, dtype=np.int32)
    np.cumsum(counts, out=indptr[1:])
    return indptr, indices, weights


def walk_packed_rows(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                     n_genes: int, *, len_path: int, reps: int, seed: int,
                     starts: Optional[np.ndarray] = None,
                     n_threads: int = 0, walker_lo: int = 0,
                     walker_hi: Optional[int] = None,
                     csr: Optional[tuple] = None,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Native walks for the walker index range [walker_lo, walker_hi) of
    the flat (repetition x start) axis -> [n_local, ceil(G/8)] uint8
    packed multi-hot rows (NOT deduplicated).

    Every walker's PRNG stream is keyed by its GLOBAL flat index, so any
    partition of the walker axis — including a multi-process shard
    (parallel/distributed.sharded_native_path_set) — reproduces exactly
    the rows the full-range call produces for those walkers.
    """
    from g2vec_tpu.native.walker_bindings import walk_paths_packed

    if starts is None:
        starts = np.arange(n_genes, dtype=np.int32)
    starts = np.asarray(starts, dtype=np.int32)
    # The C++ side indexes visited[] and indptr[] with these without
    # checks — bound them here, once, at the language boundary. A
    # precomputed ``csr`` skips the O(E) edge scans (the caller ran them
    # when it built the CSR through this function once already).
    check_arrays = (("starts", starts),) if csr is not None \
        else (("starts", starts), ("dst", dst), ("src", src))
    for name, arr in check_arrays:
        if arr.size and (arr.min() < 0 or arr.max() >= n_genes):
            raise ValueError(
                f"{name} contains node ids outside [0, {n_genes})")
    n_starts = starts.shape[0]
    total = n_starts * reps
    walker_hi = total if walker_hi is None else walker_hi
    if not (0 <= walker_lo <= walker_hi <= total):
        raise ValueError(
            f"walker range [{walker_lo}, {walker_hi}) outside [0, {total}]")
    all_starts = np.tile(starts, reps)[walker_lo:walker_hi]
    # Stream identity = rep * n_starts + i, i.e. (repetition, start-index)
    # within THIS backend's counter-based PRNG family: adding repetitions
    # extends (never reshuffles) the stream family, and slicing the walker
    # axis never re-keys anyone. The device walker keys its own streams
    # differently (split(key, reps) + fold_in), so the two backends are
    # each deterministic but not cross-identical.
    stream_ids = np.arange(walker_lo, walker_hi, dtype=np.uint64)

    # ``csr`` lets a per-shard caller (walk_shard) pay the O(E log E)
    # edge sort once per group instead of once per shard; values are
    # exactly edges_to_csr's, so the walks cannot tell the difference.
    indptr, indices, weights = (csr if csr is not None
                                else edges_to_csr(src, dst, w, n_genes))
    # The sampler emits np.packbits-layout multi-hot rows directly (bits
    # set inside the C++ walk loop): no [W, n_genes] dense expansion on
    # either side of the boundary — at bundled scale the old
    # expand-and-packbits pass cost more than the walks themselves.
    n_local = walker_hi - walker_lo
    threads = min(resolve_sampler_threads(n_threads), max(n_local, 1))
    if threads <= 1 or n_local <= RANGE_CHUNK:
        # Degenerate/small cases skip the pool; the C++ call is told 1
        # thread — the Python pool is the only fan-out layer, so thread
        # accounting has a single owner.
        return walk_paths_packed(indptr, indices, weights, n_genes,
                                 all_starts, stream_ids, len_path, seed,
                                 n_threads=1, out=out)
    nbytes = (n_genes + 7) // 8
    if out is None:
        out = np.empty((n_local, nbytes), dtype=np.uint8)
    # Contiguous ranges of at most RANGE_CHUNK walkers (but no more tasks
    # than needed for ``threads``-way parallelism x a small queue depth).
    chunk = max(RANGE_CHUNK, -(-n_local // (threads * 8)))
    futures = []
    pool = _pool(threads)
    for lo in range(0, n_local, chunk):
        hi = min(lo + chunk, n_local)
        futures.append(pool.submit(
            walk_paths_packed, indptr, indices, weights, n_genes,
            all_starts[lo:hi], stream_ids[lo:hi], len_path, seed,
            1, out[lo:hi]))
    for f in futures:
        f.result()      # propagate the first worker exception, if any
    return out


@dataclass(frozen=True)
class ShardPlan:
    """The deterministic shard decomposition of one run's walker axis.

    The streaming trainer (train/stream.py) consumes the two groups'
    walks as fixed-size SHARDS instead of one monolithic path set. A
    shard is a pure function of (shard index, plan): shard ``s`` holds
    the START-GENE range ``[s*k, (s+1)*k)`` with ALL its repetitions,
    for BOTH groups (the flat walker axis is rep-major —
    ``tile(starts, reps)`` — so one shard is ``reps`` strided slices of
    each group's axis). Start-major sharding is load-bearing for the
    per-shard common-path filter: every copy of a start gene's walks —
    all reps, both groups — lands in ONE shard, so degenerate common
    paths and cross-rep duplicates are caught locally with O(shard)
    memory, where rep-major shards would scatter them (measured:
    rep-major sharding leaks ~45% duplicate/common rows into training
    and costs ~0.2 val-ACC on the bundled-scale synthetic).

    Because per-walker PRNG streams are keyed by GLOBAL walker index
    (module docstring), shard contents are bit-identical at any thread
    count, any ring depth, and any emission/consumption interleaving —
    the determinism contract tests/test_stream.py pins.
    """

    n_starts: int           # common genes (each group's start list)
    reps: int
    starts_per_shard: int   # k
    len_path: int

    @property
    def n_walkers(self) -> int:
        """Per group: the flat walker-axis length."""
        return self.n_starts * self.reps

    @property
    def n_shards(self) -> int:
        return -(-self.n_starts // self.starts_per_shard)

    @property
    def rows_per_shard(self) -> int:
        """Nominal rows in a full shard (both groups, all reps)."""
        return 2 * self.starts_per_shard * self.reps

    def start_range(self, shard: int) -> tuple:
        """[lo, hi) of the start-gene axis covered by ``shard``."""
        lo = shard * self.starts_per_shard
        return lo, min(lo + self.starts_per_shard, self.n_starts)

    def group_rows(self, shard: int) -> int:
        """Rows ``shard`` holds per group."""
        lo, hi = self.start_range(shard)
        return (hi - lo) * self.reps


def plan_shards(n_genes: int, reps: int, shard_paths: int, *,
                len_path: int) -> ShardPlan:
    """Shard the walker axis into ~``shard_paths``-row shards
    (``shard_paths`` counts BOTH groups' rows across all reps; 0 = auto).

    Sizing targets matrix-multiply-shaped batches (arXiv:1611.06172's
    minibatch recipe): big enough that the per-shard device dispatch
    amortizes, small enough that a handful of in-flight shards bound host
    memory even at million-node scale.
    """
    if shard_paths < 0:
        raise ValueError(f"shard_paths must be >= 0, got {shard_paths}")
    if shard_paths == 0:
        shard_paths = _AUTO_SHARD_PATHS
    starts_per_shard = max(1, min(shard_paths // (2 * reps), n_genes))
    return ShardPlan(n_starts=n_genes, reps=reps,
                     starts_per_shard=starts_per_shard, len_path=len_path)


#: Auto --shard-paths: 4096 rows ~= the trainer's packing chunk and a few
#: MB of packed bits even at 100k genes — device-dispatch amortization
#: without meaningful host-memory cost.
_AUTO_SHARD_PATHS = 4096


def walk_shard(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
               n_genes: int, plan: ShardPlan, shard: int, *, seed: int,
               n_threads: int = 0, csr: Optional[tuple] = None,
               starts: Optional[np.ndarray] = None) -> np.ndarray:
    """One group's rows for shard ``shard`` of ``plan`` ->
    [group_rows, ceil(G/8)] uint8 packed multi-hot rows (NOT
    deduplicated; rep-major within the shard — rep r's block holds
    walkers ``[r*n_starts + lo, r*n_starts + hi)`` in walker order, so
    every row's bytes are exactly the full-range call's for that global
    walker index).

    A re-invocable pure function of (plan, shard, seed): the spool
    integrity layer re-walks a shard whose bytes failed verification,
    and determinism guarantees the retry reproduces the original
    emission exactly. The per-rep blocks fan out over the module's
    sampler pool (disjoint output slices, same bit-identity argument as
    walk_packed_rows' range fan-out).

    ``starts`` restricts the start-gene list to an explicit subset (the
    ``--walk-starts`` volume budget at million-node scale,
    parallel/shard.subset_starts); the plan's ``n_starts`` must then be
    ``len(starts)`` — walker/stream identities are indices into the
    subset, so shard contents stay deterministic in (plan, shard, seed,
    starts) regardless of rank ownership or thread count.
    """
    if starts is not None and len(starts) != plan.n_starts:
        raise ValueError(
            f"plan.n_starts ({plan.n_starts}) must match len(starts) "
            f"({len(starts)})")
    lo, hi = plan.start_range(shard)
    k = hi - lo
    nbytes = (n_genes + 7) // 8
    out = np.empty((k * plan.reps, nbytes), dtype=np.uint8)
    threads = min(resolve_sampler_threads(n_threads), plan.reps)

    def _block(r: int):
        return walk_packed_rows(
            src, dst, w, n_genes, len_path=plan.len_path, reps=plan.reps,
            seed=seed, starts=starts, walker_lo=r * plan.n_starts + lo,
            walker_hi=r * plan.n_starts + hi, n_threads=1, csr=csr,
            out=out[r * k:(r + 1) * k])

    if threads <= 1 or plan.reps <= 1:
        for r in range(plan.reps):
            _block(r)
    else:
        pool = _pool(threads)
        for f in [pool.submit(_block, r) for r in range(plan.reps)]:
            f.result()
    return out


@dataclass
class WalkStateBatch:
    """Explicit, relocatable walk state for a batch of walkers — the
    resumable form of the implicit per-walker state inside walk_range
    (native/walker.cpp).

    Keyed by GLOBAL walker index through ``row`` (the walker's row within
    its shard-group block, rep-major — exactly walk_shard's layout), so a
    walk produces identical bytes no matter which rank (or how many
    ranks, in how many pieces) executes it: ``rng`` is the walker's raw
    splitmix64 state — one fixed-constant advance per uniform draw — and
    the visited mask is reconstructed by replaying ``paths``. The
    edge-partitioned walk engine (parallel/shard.py) suspends batches at
    partition boundaries, ships them to the rank owning ``cur``'s
    adjacency row, and resumes them there bit-identically.
    """

    row: np.ndarray      # int32 [M] row index within the shard-group
    cur: np.ndarray      # int32 [M] current gene (path tail)
    rng: np.ndarray      # uint64 [M] raw splitmix64 state
    pos: np.ndarray      # int32 [M] nodes taken so far (>= 1)
    paths: np.ndarray    # int32 [M, len_path] path prefix, -1 padded

    def __len__(self) -> int:
        return self.row.shape[0]

    def take(self, idx: np.ndarray) -> "WalkStateBatch":
        return WalkStateBatch(
            row=np.ascontiguousarray(self.row[idx]),
            cur=np.ascontiguousarray(self.cur[idx]),
            rng=np.ascontiguousarray(self.rng[idx]),
            pos=np.ascontiguousarray(self.pos[idx]),
            paths=np.ascontiguousarray(self.paths[idx]))

    @staticmethod
    def concat(batches: "list[WalkStateBatch]") -> "WalkStateBatch":
        return WalkStateBatch(
            row=np.concatenate([b.row for b in batches]),
            cur=np.concatenate([b.cur for b in batches]),
            rng=np.concatenate([b.rng for b in batches]),
            pos=np.concatenate([b.pos for b in batches]),
            paths=np.concatenate([b.paths for b in batches], axis=0))

    @staticmethod
    def empty(len_path: int) -> "WalkStateBatch":
        return WalkStateBatch(
            row=np.zeros(0, np.int32), cur=np.zeros(0, np.int32),
            rng=np.zeros(0, np.uint64), pos=np.zeros(0, np.int32),
            paths=np.zeros((0, len_path), np.int32))


def shard_walk_states(plan: ShardPlan, shard: int, *, seed: int,
                      starts: Optional[np.ndarray] = None) -> WalkStateBatch:
    """Initial :class:`WalkStateBatch` for every walker of ``shard`` —
    row order (rep-major) and PRNG streams exactly match walk_shard's,
    so advancing these states to completion and packing the paths
    reproduces walk_shard's rows byte-for-byte."""
    from g2vec_tpu.native.walker_bindings import init_walk_state

    if starts is not None and len(starts) != plan.n_starts:
        raise ValueError(
            f"plan.n_starts ({plan.n_starts}) must match len(starts) "
            f"({len(starts)})")
    lo, hi = plan.start_range(shard)
    k = hi - lo
    sub = (np.arange(lo, hi, dtype=np.int32) if starts is None
           else np.ascontiguousarray(starts[lo:hi], dtype=np.int32))
    start_col = np.tile(sub, plan.reps)
    wids = (np.arange(plan.reps, dtype=np.uint64)[:, None]
            * np.uint64(plan.n_starts)
            + np.arange(lo, hi, dtype=np.uint64)[None, :]).ravel()
    n = k * plan.reps
    paths = np.full((n, plan.len_path), -1, np.int32)
    paths[:, 0] = start_col
    return WalkStateBatch(
        row=np.arange(n, dtype=np.int32),
        cur=np.ascontiguousarray(start_col),
        rng=init_walk_state(seed, wids),
        pos=np.ones(n, np.int32),
        paths=paths)


def advance_walk_states(states: WalkStateBatch, csr: tuple, n_genes: int,
                        avail: np.ndarray, len_path: int,
                        n_threads: int = 0) -> np.ndarray:
    """Advance every walk in ``states`` IN PLACE over an
    availability-masked CSR until it finishes (full length or dead end)
    or suspends on a row this rank does not hold. Returns the [M] uint8
    status array (0 finished, 1 suspended)."""
    from g2vec_tpu.native.walker_bindings import walk_partial

    indptr, indices, weights = csr
    return walk_partial(indptr, indices, weights, n_genes, avail,
                        states.cur, states.rng, states.pos, states.paths,
                        len_path, n_threads=n_threads)


def pack_finished_paths(paths: np.ndarray, n_genes: int,
                        out: Optional[np.ndarray] = None) -> np.ndarray:
    """Pack [M, len_path] finished paths into walk_shard's packed-row
    encoding (native/walker_bindings.pack_paths)."""
    from g2vec_tpu.native.walker_bindings import pack_paths

    return pack_paths(paths, n_genes, out=out)


def generate_path_set_native(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                             n_genes: int, *, len_path: int, reps: int,
                             seed: int, starts: Optional[np.ndarray] = None,
                             n_threads: int = 0) -> Set[bytes]:
    """All-sources x reps native walks -> set of packed multi-hot rows.

    Mirrors generate_pathSet (ref: G2Vec.py:324-352) on the host: every
    gene a start node, ``reps`` times, results set-deduplicated. Raises
    RuntimeError when the native library cannot be built (no C++
    toolchain) — the pipeline surfaces that as a config error rather than
    silently changing backends (the device walker's seeded outputs are a
    byte-golden contract).
    """
    packed = walk_packed_rows(src, dst, w, n_genes, len_path=len_path,
                              reps=reps, seed=seed, starts=starts,
                              n_threads=n_threads)
    return {row.tobytes() for row in packed}
