"""Native-CPU path generation — the host fallback twin of ops/walker.py.

SURVEY.md §2 names two optional native components for this framework; the
C++ TSV reader is one, this sampler is the other: on a host with no
accelerator the JAX lockstep walker pays XLA-on-CPU overheads it was never
designed for, while the reference's own per-node loop costs O(G) per step
(the dense-row deepcopy at ref: G2Vec.py:334). The native sampler walks
CSR rows at O(out_degree + path_len) per step across OS threads
(native/walker.cpp). It is the measured DEFAULT on every host, chip
attached or not: the walk step is branchy pointer-chasing with no matmul,
so even the real v5e device walker stays an order of magnitude behind
(~98k native vs >6.1k device walks/s — the measured table in
ops/backend.py); the device walker's remaining role is mesh-sharded
neighbor tables.

Same output contract as :func:`g2vec_tpu.ops.walker.generate_path_set`:
a set of np.packbits-encoded multi-hot rows over the sorted gene order —
dedup and the downstream integrate/count/train stages cannot tell the
backends apart. Same walk SEMANTICS (no revisit, weight-proportional
sampling, dead-end stop, every gene a start node reps times,
ref: G2Vec.py:324-352); per-seed deterministic for any thread count
(streams are keyed by (seed, repetition, start-index) within this
backend's own counter-based PRNG family). The two backends draw from
different PRNG families — the device walker derives its streams via
jax.random split/fold_in — so their path sets differ for the same seed;
each is individually deterministic, exactly the documented dense/sparse
caveat in generate_path_set.
"""
from __future__ import annotations

from typing import Optional, Set

import numpy as np


def edges_to_csr(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                 n_genes: int):
    """(src, dst, w) edge lists -> CSR (indptr [G+1], indices [E], w [E]).

    Directed, duplicate edges kept — identical multiset semantics to the
    padded neighbor_table (ops/graph.py), just without the max-degree
    padding that a CPU scan does not need.
    """
    order = np.argsort(src, kind="stable")
    indices = np.ascontiguousarray(dst[order], dtype=np.int32)
    weights = np.ascontiguousarray(w[order], dtype=np.float32)
    counts = np.bincount(src, minlength=n_genes)
    indptr = np.zeros(n_genes + 1, dtype=np.int32)
    np.cumsum(counts, out=indptr[1:])
    return indptr, indices, weights


def walk_packed_rows(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                     n_genes: int, *, len_path: int, reps: int, seed: int,
                     starts: Optional[np.ndarray] = None,
                     n_threads: int = 0, walker_lo: int = 0,
                     walker_hi: Optional[int] = None) -> np.ndarray:
    """Native walks for the walker index range [walker_lo, walker_hi) of
    the flat (repetition x start) axis -> [n_local, ceil(G/8)] uint8
    packed multi-hot rows (NOT deduplicated).

    Every walker's PRNG stream is keyed by its GLOBAL flat index, so any
    partition of the walker axis — including a multi-process shard
    (parallel/distributed.sharded_native_path_set) — reproduces exactly
    the rows the full-range call produces for those walkers.
    """
    from g2vec_tpu.native.walker_bindings import walk_paths_packed

    if starts is None:
        starts = np.arange(n_genes, dtype=np.int32)
    starts = np.asarray(starts, dtype=np.int32)
    # The C++ side indexes visited[] and indptr[] with these without
    # checks — bound them here, once, at the language boundary.
    for name, arr in (("starts", starts), ("dst", dst)):
        if arr.size and (arr.min() < 0 or arr.max() >= n_genes):
            raise ValueError(
                f"{name} contains node ids outside [0, {n_genes})")
    if src.size and (src.min() < 0 or src.max() >= n_genes):
        raise ValueError(f"src contains node ids outside [0, {n_genes})")
    n_starts = starts.shape[0]
    total = n_starts * reps
    walker_hi = total if walker_hi is None else walker_hi
    if not (0 <= walker_lo <= walker_hi <= total):
        raise ValueError(
            f"walker range [{walker_lo}, {walker_hi}) outside [0, {total}]")
    all_starts = np.tile(starts, reps)[walker_lo:walker_hi]
    # Stream identity = rep * n_starts + i, i.e. (repetition, start-index)
    # within THIS backend's counter-based PRNG family: adding repetitions
    # extends (never reshuffles) the stream family, and slicing the walker
    # axis never re-keys anyone. The device walker keys its own streams
    # differently (split(key, reps) + fold_in), so the two backends are
    # each deterministic but not cross-identical.
    stream_ids = np.arange(walker_lo, walker_hi, dtype=np.uint64)

    indptr, indices, weights = edges_to_csr(src, dst, w, n_genes)
    # The sampler emits np.packbits-layout multi-hot rows directly (bits
    # set inside the C++ walk loop): no [W, n_genes] dense expansion on
    # either side of the boundary — at bundled scale the old
    # expand-and-packbits pass cost more than the walks themselves.
    return walk_paths_packed(indptr, indices, weights, n_genes,
                             all_starts, stream_ids, len_path, seed,
                             n_threads)


def generate_path_set_native(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                             n_genes: int, *, len_path: int, reps: int,
                             seed: int, starts: Optional[np.ndarray] = None,
                             n_threads: int = 0) -> Set[bytes]:
    """All-sources x reps native walks -> set of packed multi-hot rows.

    Mirrors generate_pathSet (ref: G2Vec.py:324-352) on the host: every
    gene a start node, ``reps`` times, results set-deduplicated. Raises
    RuntimeError when the native library cannot be built (no C++
    toolchain) — the pipeline surfaces that as a config error rather than
    silently changing backends (the device walker's seeded outputs are a
    byte-golden contract).
    """
    packed = walk_packed_rows(src, dst, w, n_genes, len_path=len_path,
                              reps=reps, seed=seed, starts=starts,
                              n_threads=n_threads)
    return {row.tobytes() for row in packed}
