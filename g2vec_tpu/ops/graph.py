"""L3 — per-group weighted adjacency from Pearson correlations, jitted.

Reference semantics (construct_adjMat, G2Vec.py:370-391; compute_PCC,
G2Vec.py:354-368): for a patient group g, each directed edge (src, dst) from
the network file gets weight |PCC(expr[:, src], expr[:, dst])| computed over
that group's samples only, kept iff strictly greater than the threshold
(0.5); all other entries are 0. The matrix is NOT symmetrized — only
``adj[src, dst]`` is written, direction straight from file column order
(SURVEY.md §7 quirk (d)). A degenerate gene (zero std over the group) gets
PCC 0 against everything (ref: G2Vec.py:359-363).

TPU design: the reference calls a per-edge Python PCC function ~216k times
per group (ref: G2Vec.py:383-385). Here the whole thing is one fused XLA
program: z-score the group's expression once, gather the two edge-endpoint
columns, take row-means of products (per-edge PCC in one vectorized pass),
threshold, and scatter into the dense [G, G] matrix. O(E·S) FLOPs instead of
Python-loop overhead; everything stays on device for the walker to consume.

For very large gene sets the dense [G, G] matrix dominates HBM (G=40k →
6.4 GB fp32); ``edge_weights`` returns the per-edge weights without the dense
scatter so a sparse/sharded walker can consume (src, dst, w) directly.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _zscore_columns(expr: jax.Array) -> jax.Array:
    """Per-gene z-score over samples; degenerate (std=0) columns -> all-zero.

    Population std (ddof=0), matching the reference's compute_PCC
    (G2Vec.py:358-363: mean/std over the group's samples, zeros on zero std).
    An all-zero z column makes every PCC involving that gene 0, which
    reproduces the reference's early-return.
    """
    mean = expr.mean(axis=0, keepdims=True)
    std = expr.std(axis=0, keepdims=True)
    # Degeneracy test is max==min (exact even in float32), not std==0: the
    # float32 std of a constant column can come out as a tiny nonzero value,
    # which would defeat the reference's zero-on-degenerate rule.
    constant = expr.max(axis=0, keepdims=True) == expr.min(axis=0, keepdims=True)
    ok = ~constant & (std > 0.0)
    return jnp.where(ok, (expr - mean) / jnp.where(ok, std, 1.0), 0.0)


@jax.jit
def edge_weights(expr_group: jax.Array, src: jax.Array, dst: jax.Array
                 ) -> jax.Array:
    """|PCC| per directed edge over one group's samples.

    ``expr_group``: [S, G] float32 (samples of ONE prognosis group);
    ``src``/``dst``: [E] int32 edge endpoint indices. Returns [E] float32.

    PCC = mean(z_src * z_dst) over samples (population normalization, exactly
    the reference's (1/n)·sum at G2Vec.py:365-367).
    """
    z = _zscore_columns(expr_group.astype(jnp.float32))   # [S, G]
    zs = z.T[src]                                         # [E, S] gather rows
    zd = z.T[dst]                                         # [E, S]
    return jnp.abs(jnp.mean(zs * zd, axis=1))


@partial(jax.jit, static_argnames=("n_genes",))
def build_adjacency(expr_group: jax.Array, src: jax.Array, dst: jax.Array,
                    n_genes: int, threshold: float = 0.5) -> jax.Array:
    """Dense directed [G, G] adjacency: |PCC| where > threshold else 0.

    Matches ref construct_adjMat (G2Vec.py:370-391): strict '>' on the
    threshold (G2Vec.py:389), only adj[src, dst] written (G2Vec.py:390).
    Duplicate edges in the file overwrite idempotently (same weight).
    """
    w = edge_weights(expr_group, src, dst)
    w = jnp.where(w > threshold, w, 0.0)
    adj = jnp.zeros((n_genes, n_genes), dtype=jnp.float32)
    return adj.at[src, dst].set(w)


def thresholded_edges(expr_group, src: np.ndarray, dst: np.ndarray,
                      threshold: float = 0.5):
    """Surviving (src, dst, |PCC|) triples as compact host arrays.

    Same filter as :func:`build_adjacency` (|PCC| strictly > threshold,
    directed, ref: G2Vec.py:389-390) without materializing the dense [G, G]
    matrix — the sparse walker consumes these directly. Duplicate (src, dst)
    pairs are collapsed to one entry (the dense scatter is idempotent, so
    this is the same graph; keeping both would double that edge's sampling
    probability in a neighbor list).
    """
    w = np.asarray(edge_weights(expr_group, jnp.asarray(src), jnp.asarray(dst)))
    keep = w > threshold
    src_k, dst_k, w_k = src[keep], dst[keep], w[keep]
    _, first = np.unique(
        src_k.astype(np.int64) * (np.max(dst_k, initial=0) + 1) + dst_k,
        return_index=True)
    first.sort()
    return src_k[first], dst_k[first], w_k[first]


def neighbor_table(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                   n_genes: int, round_pow2: bool = True):
    """Padded out-neighbor lists: ([G, D] int32 indices, [G, D] f32 weights).

    D is the max out-degree, rounded up to a power of two (bounds XLA
    recompiles across datasets to log2 buckets). Padding slots carry index 0
    and weight 0 — the walker masks on weight, so they are unreachable.
    This is the TPU-native sparse transition format: per-step sampling cost
    drops from O(W*G) (dense row gather) to O(W*D), and HBM holds 2*G*D
    values instead of G^2.
    """
    order = np.argsort(src, kind="stable")
    src_s, dst_s, w_s = src[order], dst[order], w[order]
    degrees = np.bincount(src_s, minlength=n_genes)
    max_deg = int(degrees.max()) if degrees.size else 0
    d = max(max_deg, 1)
    if round_pow2:
        d = 1 << (d - 1).bit_length()
    nbr_idx = np.zeros((n_genes, d), dtype=np.int32)
    nbr_w = np.zeros((n_genes, d), dtype=np.float32)
    if src_s.size:
        # Slot of edge e = its rank within its source's contiguous block.
        group_start = np.concatenate(
            [[0], np.cumsum(degrees)[:-1]]).astype(np.int64)
        slots = np.arange(src_s.size, dtype=np.int64) - group_start[src_s]
        nbr_idx[src_s, slots] = dst_s
        nbr_w[src_s, slots] = w_s
    return nbr_idx, nbr_w
