"""L3 — per-group weighted adjacency from Pearson correlations, jitted.

Reference semantics (construct_adjMat, G2Vec.py:370-391; compute_PCC,
G2Vec.py:354-368): for a patient group g, each directed edge (src, dst) from
the network file gets weight |PCC(expr[:, src], expr[:, dst])| computed over
that group's samples only, kept iff strictly greater than the threshold
(0.5); all other entries are 0. The matrix is NOT symmetrized — only
``adj[src, dst]`` is written, direction straight from file column order
(SURVEY.md §7 quirk (d)). A degenerate gene (zero std over the group) gets
PCC 0 against everything (ref: G2Vec.py:359-363).

TPU design: the reference calls a per-edge Python PCC function ~216k times
per group (ref: G2Vec.py:383-385). Here the whole thing is one fused XLA
program: z-score the group's expression once, gather the two edge-endpoint
columns, take row-means of products (per-edge PCC in one vectorized pass),
threshold, and scatter into the dense [G, G] matrix. O(E·S) FLOPs instead of
Python-loop overhead; everything stays on device for the walker to consume.

For very large gene sets the dense [G, G] matrix dominates HBM (G=40k →
6.4 GB fp32); ``edge_weights`` returns the per-edge weights without the dense
scatter so a sparse/sharded walker can consume (src, dst, w) directly.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def _zscore_columns(expr: jax.Array) -> jax.Array:
    """Per-gene z-score over samples; degenerate (std=0) columns -> all-zero.

    Population std (ddof=0), matching the reference's compute_PCC
    (G2Vec.py:358-363: mean/std over the group's samples, zeros on zero std).
    An all-zero z column makes every PCC involving that gene 0, which
    reproduces the reference's early-return.
    """
    mean = expr.mean(axis=0, keepdims=True)
    std = expr.std(axis=0, keepdims=True)
    # Degeneracy test is max==min (exact even in float32), not std==0: the
    # float32 std of a constant column can come out as a tiny nonzero value,
    # which would defeat the reference's zero-on-degenerate rule.
    constant = expr.max(axis=0, keepdims=True) == expr.min(axis=0, keepdims=True)
    ok = ~constant & (std > 0.0)
    return jnp.where(ok, (expr - mean) / jnp.where(ok, std, 1.0), 0.0)


@jax.jit
def edge_weights(expr_group: jax.Array, src: jax.Array, dst: jax.Array
                 ) -> jax.Array:
    """|PCC| per directed edge over one group's samples.

    ``expr_group``: [S, G] float32 (samples of ONE prognosis group);
    ``src``/``dst``: [E] int32 edge endpoint indices. Returns [E] float32.

    PCC = mean(z_src * z_dst) over samples (population normalization, exactly
    the reference's (1/n)·sum at G2Vec.py:365-367).
    """
    z = _zscore_columns(expr_group.astype(jnp.float32))   # [S, G]
    zs = z.T[src]                                         # [E, S] gather rows
    zd = z.T[dst]                                         # [E, S]
    return jnp.abs(jnp.mean(zs * zd, axis=1))


@partial(jax.jit, static_argnames=("n_genes",))
def build_adjacency(expr_group: jax.Array, src: jax.Array, dst: jax.Array,
                    n_genes: int, threshold: float = 0.5) -> jax.Array:
    """Dense directed [G, G] adjacency: |PCC| where > threshold else 0.

    Matches ref construct_adjMat (G2Vec.py:370-391): strict '>' on the
    threshold (G2Vec.py:389), only adj[src, dst] written (G2Vec.py:390).
    Duplicate edges in the file overwrite idempotently (same weight).
    """
    w = edge_weights(expr_group, src, dst)
    w = jnp.where(w > threshold, w, 0.0)
    adj = jnp.zeros((n_genes, n_genes), dtype=jnp.float32)
    return adj.at[src, dst].set(w)
