"""L2 — preprocessing / alignment.

The single global invariant of the whole pipeline lives here: the gene order
is the SORTED intersection of the network's and expression file's gene sets
(ref: G2Vec.py:420-426). Every downstream index — adjacency rows, embedding
rows, L-group indices, output row order — is in this order.

Components (ref file:line):
- match_labels        (G2Vec.py:428-434) — with a real error message
- find_common_genes   (G2Vec.py:420-426)
- restrict_network    (G2Vec.py:393-402) — keeps directed edges whose both
  endpoints are common; de-duplicates nothing (file may contain repeats, the
  adjacency write is idempotent)
- restrict_data       (G2Vec.py:404-418) — reorders/clips expression columns
- edges_to_indices    — new: edge list -> int32 index arrays for the device
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from g2vec_tpu.io.readers import ExpressionData, NetworkData


class SampleMismatchError(ValueError):
    """An expression-file sample has no clinical label (ref: G2Vec.py:432-433)."""


def match_labels(clinical: Dict[str, int], samples: np.ndarray) -> np.ndarray:
    """Map expression-file sample order -> int labels.

    The reference bare-excepts and exit(1)s (G2Vec.py:429-433); we raise a
    typed error naming the offending samples so callers can act on it.
    """
    missing = [s for s in samples if s not in clinical]
    if missing:
        preview = ", ".join(missing[:5])
        raise SampleMismatchError(
            f"{len(missing)} expression sample(s) have no clinical label "
            f"(first few: {preview}). Please check sample names.")
    return np.array([clinical[s] for s in samples], dtype=np.int32)


def find_common_genes(network_genes: set, data_genes: np.ndarray) -> List[str]:
    """Sorted intersection — defines the global gene index (ref: G2Vec.py:420-426)."""
    return sorted(set(network_genes) & set(data_genes))


def restrict_network(network: NetworkData, common_genes: List[str]) -> NetworkData:
    """Keep directed edges with both endpoints common (ref: G2Vec.py:393-402).

    Matches the reference quirk of setting the result's gene set to the whole
    common set (not just genes with surviving edges, ref: G2Vec.py:400-401).
    """
    common = set(common_genes)
    edges = [e for e in network.edges if e[0] in common and e[1] in common]
    return NetworkData(edges=edges, genes=common)


def restrict_data(data: ExpressionData, common_genes: List[str]) -> ExpressionData:
    """Reorder/clip expression columns to the sorted common list (ref: G2Vec.py:404-412)."""
    gene2idx = {g: i for i, g in enumerate(data.gene)}
    idx = np.array([gene2idx[g] for g in common_genes], dtype=np.int64)
    return ExpressionData(
        sample=data.sample.copy(),
        gene=np.array(common_genes),
        expr=np.ascontiguousarray(data.expr[:, idx]),
        label=None if data.label is None else data.label.copy(),
    )


def subsample_patients(data: ExpressionData, fraction: float,
                       seed: int, with_replacement: bool = False) -> ExpressionData:
    """Keep a stratified, seeded ``fraction`` of patients per label class.

    The paper's biomarker validation protocol repeats the pipeline over
    patient resamples; this makes one resample a deterministic function of
    (fraction, seed) so a manifest lane and a solo run agree byte-for-byte.
    Per label class, ``max(2, round(fraction * n_class))`` patients are
    kept (2 is the floor the ddof=1 t-score needs), chosen by a seeded
    permutation of the class's positions in file order; the kept rows stay
    in their original relative order, so downstream per-column statistics
    see a pure row subset.

    With ``with_replacement=True`` this becomes a stratified bootstrap
    resample: the same number of rows is DRAWN with replacement per class,
    so a patient can appear multiple times (its expression row is
    duplicated). Draws are re-taken, deterministically, until the class
    has at least 2 distinct patients. Row order is still ascending file
    order (duplicates adjacent), keeping the row-subset layout invariants.
    """
    if data.label is None:
        raise ValueError("subsample_patients needs matched labels "
                         "(call match_labels first)")
    if not (0.0 < fraction <= 1.0):
        raise ValueError(f"subsample fraction must be in (0,1], got {fraction}")
    rng = np.random.default_rng(seed)
    if with_replacement:
        parts = []
        for cls in (0, 1):
            pos = np.nonzero(data.label == cls)[0]
            if pos.size < 2:
                raise ValueError(
                    f"label class {cls} has only {pos.size} patient(s); "
                    f"cannot subsample")
            n_draw = min(pos.size, max(2, int(round(fraction * pos.size))))
            # Same rng consumption order (class 0 then 1). Redraw until the
            # resample spans >=2 distinct patients (ddof=1 floor); the loop
            # is deterministic because the rng stream is.
            draw = rng.choice(pos, size=n_draw, replace=True)
            while np.unique(draw).size < 2:
                draw = rng.choice(pos, size=n_draw, replace=True)
            parts.append(draw)
        rows = np.sort(np.concatenate(parts))
        return ExpressionData(
            sample=data.sample[rows].copy(),
            gene=data.gene,
            expr=np.ascontiguousarray(data.expr[rows]),
            label=data.label[rows].copy(),
        )
    keep = np.zeros(len(data.label), dtype=bool)
    for cls in (0, 1):
        pos = np.nonzero(data.label == cls)[0]
        if pos.size < 2:
            raise ValueError(
                f"label class {cls} has only {pos.size} patient(s); "
                f"cannot subsample")
        n_keep = min(pos.size, max(2, int(round(fraction * pos.size))))
        # One rng consumed in class order (0 then 1): deterministic and
        # independent of the other class's size changing.
        keep[np.sort(rng.permutation(pos)[:n_keep])] = True
    return ExpressionData(
        sample=data.sample[keep].copy(),
        gene=data.gene,
        expr=np.ascontiguousarray(data.expr[keep]),
        label=data.label[keep].copy(),
    )


def fold_assignments(labels: np.ndarray, n_folds: int, seed: int) -> np.ndarray:
    """Stratified fold ids, one per patient row: seeded and group-balanced.

    Each label class is permuted independently (one rng, class order 0
    then 1, mirroring subsample_patients) and dealt round-robin across the
    folds, so per-class fold sizes differ by at most one. Every class must
    leave >=2 patients in each training split (the ddof=1 floor) and put
    >=1 patient in each held-out fold, otherwise a ValueError names the
    class.
    """
    if labels is None:
        raise ValueError("fold_assignments needs matched labels")
    if n_folds < 2:
        raise ValueError(f"n_folds must be >= 2, got {n_folds}")
    folds = np.full(len(labels), -1, dtype=np.int32)
    rng = np.random.default_rng(seed)
    for cls in (0, 1):
        pos = np.nonzero(labels == cls)[0]
        if pos.size < n_folds:
            raise ValueError(
                f"label class {cls} has {pos.size} patient(s); cannot "
                f"stratify into {n_folds} folds")
        max_in_fold = -(-pos.size // n_folds)  # ceil
        if pos.size - max_in_fold < 2:
            raise ValueError(
                f"label class {cls} has {pos.size} patient(s); a "
                f"{n_folds}-fold training split would drop below 2")
        order = rng.permutation(pos)
        folds[order] = np.arange(order.size, dtype=np.int32) % n_folds
    return folds


def fold_cohort(data: ExpressionData, n_folds: int, fold: int,
                seed: int) -> ExpressionData:
    """Training cohort for one CV fold: every patient NOT in ``fold``.

    All folds of a scenario share one ``fold_assignments`` partition (same
    seed), so the k cohorts are complements of disjoint held-out sets.
    """
    if not (0 <= fold < n_folds):
        raise ValueError(f"fold must be in [0, {n_folds}), got {fold}")
    keep = fold_assignments(data.label, n_folds, seed) != fold
    return ExpressionData(
        sample=data.sample[keep].copy(),
        gene=data.gene,
        expr=np.ascontiguousarray(data.expr[keep]),
        label=data.label[keep].copy(),
    )


def permute_labels(labels: np.ndarray, seed: int) -> np.ndarray:
    """Seeded label shuffle (a permutation-null draw); input untouched."""
    return np.random.default_rng(seed).permutation(labels)


def make_gene2idx(genes: np.ndarray) -> Dict[str, int]:
    """Gene symbol -> global index (ref: G2Vec.py:414-418)."""
    return {g: i for i, g in enumerate(genes)}


def edges_to_indices(network: NetworkData,
                     gene2idx: Dict[str, int]) -> Tuple[np.ndarray, np.ndarray]:
    """Edge list -> (src_idx, dst_idx) int32 arrays, file order preserved.

    This is the device-friendly form of the edge list: the PCC adjacency op
    scatters |PCC| weights at these coordinates (direction taken from file
    column order, as in ref: G2Vec.py:379-390 — the graph is NOT symmetrized).
    """
    if not network.edges:
        return (np.zeros(0, dtype=np.int32), np.zeros(0, dtype=np.int32))
    src = np.array([gene2idx[e[0]] for e in network.edges], dtype=np.int32)
    dst = np.array([gene2idx[e[1]] for e in network.edges], dtype=np.int32)
    return src, dst
