"""L2 — preprocessing / alignment.

The single global invariant of the whole pipeline lives here: the gene order
is the SORTED intersection of the network's and expression file's gene sets
(ref: G2Vec.py:420-426). Every downstream index — adjacency rows, embedding
rows, L-group indices, output row order — is in this order.

Components (ref file:line):
- match_labels        (G2Vec.py:428-434) — with a real error message
- find_common_genes   (G2Vec.py:420-426)
- restrict_network    (G2Vec.py:393-402) — keeps directed edges whose both
  endpoints are common; de-duplicates nothing (file may contain repeats, the
  adjacency write is idempotent)
- restrict_data       (G2Vec.py:404-418) — reorders/clips expression columns
- edges_to_indices    — new: edge list -> int32 index arrays for the device
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from g2vec_tpu.io.readers import ExpressionData, NetworkData


class SampleMismatchError(ValueError):
    """An expression-file sample has no clinical label (ref: G2Vec.py:432-433)."""


def match_labels(clinical: Dict[str, int], samples: np.ndarray) -> np.ndarray:
    """Map expression-file sample order -> int labels.

    The reference bare-excepts and exit(1)s (G2Vec.py:429-433); we raise a
    typed error naming the offending samples so callers can act on it.
    """
    missing = [s for s in samples if s not in clinical]
    if missing:
        preview = ", ".join(missing[:5])
        raise SampleMismatchError(
            f"{len(missing)} expression sample(s) have no clinical label "
            f"(first few: {preview}). Please check sample names.")
    return np.array([clinical[s] for s in samples], dtype=np.int32)


def find_common_genes(network_genes: set, data_genes: np.ndarray) -> List[str]:
    """Sorted intersection — defines the global gene index (ref: G2Vec.py:420-426)."""
    return sorted(set(network_genes) & set(data_genes))


def restrict_network(network: NetworkData, common_genes: List[str]) -> NetworkData:
    """Keep directed edges with both endpoints common (ref: G2Vec.py:393-402).

    Matches the reference quirk of setting the result's gene set to the whole
    common set (not just genes with surviving edges, ref: G2Vec.py:400-401).
    """
    common = set(common_genes)
    edges = [e for e in network.edges if e[0] in common and e[1] in common]
    return NetworkData(edges=edges, genes=common)


def restrict_data(data: ExpressionData, common_genes: List[str]) -> ExpressionData:
    """Reorder/clip expression columns to the sorted common list (ref: G2Vec.py:404-412)."""
    gene2idx = {g: i for i, g in enumerate(data.gene)}
    idx = np.array([gene2idx[g] for g in common_genes], dtype=np.int64)
    return ExpressionData(
        sample=data.sample.copy(),
        gene=np.array(common_genes),
        expr=np.ascontiguousarray(data.expr[:, idx]),
        label=None if data.label is None else data.label.copy(),
    )


def subsample_patients(data: ExpressionData, fraction: float,
                       seed: int) -> ExpressionData:
    """Keep a stratified, seeded ``fraction`` of patients per label class.

    The paper's biomarker validation protocol repeats the pipeline over
    patient resamples; this makes one resample a deterministic function of
    (fraction, seed) so a manifest lane and a solo run agree byte-for-byte.
    Per label class, ``max(2, round(fraction * n_class))`` patients are
    kept (2 is the floor the ddof=1 t-score needs), chosen by a seeded
    permutation of the class's positions in file order; the kept rows stay
    in their original relative order, so downstream per-column statistics
    see a pure row subset.
    """
    if data.label is None:
        raise ValueError("subsample_patients needs matched labels "
                         "(call match_labels first)")
    if not (0.0 < fraction <= 1.0):
        raise ValueError(f"subsample fraction must be in (0,1], got {fraction}")
    rng = np.random.default_rng(seed)
    keep = np.zeros(len(data.label), dtype=bool)
    for cls in (0, 1):
        pos = np.nonzero(data.label == cls)[0]
        if pos.size < 2:
            raise ValueError(
                f"label class {cls} has only {pos.size} patient(s); "
                f"cannot subsample")
        n_keep = min(pos.size, max(2, int(round(fraction * pos.size))))
        # One rng consumed in class order (0 then 1): deterministic and
        # independent of the other class's size changing.
        keep[np.sort(rng.permutation(pos)[:n_keep])] = True
    return ExpressionData(
        sample=data.sample[keep].copy(),
        gene=data.gene,
        expr=np.ascontiguousarray(data.expr[keep]),
        label=data.label[keep].copy(),
    )


def make_gene2idx(genes: np.ndarray) -> Dict[str, int]:
    """Gene symbol -> global index (ref: G2Vec.py:414-418)."""
    return {g: i for i, g in enumerate(genes)}


def edges_to_indices(network: NetworkData,
                     gene2idx: Dict[str, int]) -> Tuple[np.ndarray, np.ndarray]:
    """Edge list -> (src_idx, dst_idx) int32 arrays, file order preserved.

    This is the device-friendly form of the edge list: the PCC adjacency op
    scatters |PCC| weights at these coordinates (direction taken from file
    column order, as in ref: G2Vec.py:379-390 — the graph is NOT symmetrized).
    """
    if not network.edges:
        return (np.zeros(0, dtype=np.int32), np.zeros(0, dtype=np.int32))
    src = np.array([gene2idx[e[0]] for e in network.edges], dtype=np.int32)
    dst = np.array([gene2idx[e[1]] for e in network.edges], dtype=np.int32)
    return src, dst
