"""Persistent on-disk caches: compile tier + walk-artifact tier.

Two costs dominate a repeat pipeline run at the same config and the same
inputs, and neither is new information the second time:

- **XLA compiles** (~20-40 s cold on a real chip for the trainer chunk +
  k-means programs). JAX already ships a persistent compilation cache;
  ``--cache-dir`` wires it to ``<dir>/xla`` (an explicit
  ``--compilation-cache`` still wins — it is the narrower flag).
- **Stage 3 walks** — the paper's "most time consuming step"
  (ref: G2Vec.py:58). A group's path set is a pure function of its
  thresholded edge list and the walk parameters, so it is cached here as
  a content-addressed artifact: the key is the sha256 of the exact CSR
  inputs (src/dst/weight arrays + n_genes) plus the walk params plus a
  VERSIONED PRNG-family tag (the two samplers draw from different
  families — ops/host_walker.py docstring — so their artifacts must
  never alias). Repeat runs skip the walks entirely; any input or
  config drift changes the key and misses.

Artifacts are verified before they are trusted (same stance as the
checkpoint manifests, whose sha256 machinery this reuses via
utils/integrity.py): every store writes ``<key>.npz`` plus a sidecar
manifest with the file's sha256; a load whose bytes do not match the
manifest — a torn write, bitrot, or an injected ``corrupt`` fault at the
``walk_cache`` seam — warns and reports a miss, and the caller's
recompute overwrites the bad entry. A cache can make a run faster; it
must never be able to make one wrong.

This module imports no jax: the bench host-only child and toy tests use
it with no backend in the process.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import warnings
from typing import Dict, Optional, Set

import numpy as np

from g2vec_tpu.resilience.faults import fault_point
from g2vec_tpu.utils.integrity import sha256_file, write_json_atomic

SCHEMA_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"

# ---------------------------------------------------------------------------
# Tier-wide hit/miss accounting (the serve daemon's /status currency).
#
# Every cache tier used to report its outcomes only as scattered event
# extras (walk_cache metrics events, autotune "source" fields, nothing at
# all for the in-process program LRUs) — fine for one run, useless for a
# long-lived daemon that needs "how warm am I?" as a single answer. Each
# tier records its outcomes here; :func:`cache_stats` snapshots them all.
# Counters are process-global and monotonically increasing (like the seq
# numbers in the metrics stream); callers needing per-window deltas
# snapshot twice and subtract.
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
_TIER_STATS: Dict[str, Dict[str, int]] = {}


def record_cache_event(tier: str, outcome: str, n: int = 1) -> None:
    """Count one cache outcome, e.g. ``("walk", "disk_hit")``,
    ``("compile", "program_hit")``, ``("autotune", "miss")``."""
    with _STATS_LOCK:
        _TIER_STATS.setdefault(tier, {})
        _TIER_STATS[tier][outcome] = _TIER_STATS[tier].get(outcome, 0) + n


def cache_stats() -> Dict[str, Dict]:
    """Snapshot of every tier's counters since process start.

    Tiers: ``walk`` (memo_hit / disk_hit / miss / verify_failed / store),
    ``compile`` (program_hit / program_miss — the in-process chunk/unpack
    program LRUs in train/trainer.py; plus ``xla_dir`` and its on-disk
    entry count when the persistent XLA tier is configured), ``autotune``
    (hit / miss / sweep — ops/packed_matmul.py's measured-plan tier).
    """
    with _STATS_LOCK:
        snap: Dict[str, Dict] = {t: dict(c) for t, c in _TIER_STATS.items()}
    snap.setdefault("walk", {})
    snap.setdefault("compile", {})
    snap.setdefault("autotune", {})
    xla_dir = _configured_xla_dir()
    if xla_dir:
        snap["compile"]["xla_dir"] = xla_dir
        try:
            snap["compile"]["xla_entries"] = len(os.listdir(xla_dir))
        except OSError:
            snap["compile"]["xla_entries"] = 0
    return snap


_configured_xla: Optional[str] = None


def _configured_xla_dir() -> Optional[str]:
    return _configured_xla or os.environ.get("JAX_COMPILATION_CACHE_DIR")

#: PRNG-family tags baked into every key. Version them on ANY change to
#: the corresponding sampler's stream derivation — a stale artifact from
#: an older stream family must miss, not load.
#:
#: NATIVE_FAMILY names the splitmix64 STREAM family, not a host/device
#: implementation: the bit-exact device sampler (ops/device_walker.py)
#: emits byte-identical packed rows for the same (CSR bytes, walk
#: params, seed), so BOTH production backends key under it — a device
#: run HITS a host-populated entry and vice versa (the cross-backend
#: cache contract, pinned in tests/test_device_walker.py). DEVICE_FAMILY
#: is the legacy jax.random lockstep walker's tag, kept so its old
#: artifacts stay addressable and can never collide with splitmix64
#: entries.
NATIVE_FAMILY = "native-splitmix64-v1"
DEVICE_FAMILY = "device-jaxrandom-v1"


def walk_cache_key(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                   n_genes: int, *, len_path: int, reps: int, seed: int,
                   family: str) -> str:
    """Content hash of everything the walk output is a function of."""
    h = hashlib.sha256()
    h.update(f"schema={SCHEMA_VERSION};family={family};"
             f"n_genes={n_genes};len_path={len_path};reps={reps};"
             f"seed={seed};".encode())
    for arr, dtype in ((src, np.int32), (dst, np.int32), (w, np.float32)):
        a = np.ascontiguousarray(np.asarray(arr), dtype=dtype)
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class WalkCache:
    """The walk-artifact tier rooted at one directory.

    ``load``/``store`` speak the pipeline's path-set currency — a set of
    np.packbits-encoded multi-hot rows — and store it as the sorted
    [n_unique, ceil(n_genes/8)] uint8 matrix (sets are unordered; sorting
    makes the artifact bytes, and therefore its sha256, deterministic).
    """

    directory: str

    def _paths(self, key: str) -> tuple:
        art = os.path.join(self.directory, f"walks-{key[:32]}.npz")
        return art, art + MANIFEST_SUFFIX

    def load(self, key: str) -> Optional[Set[bytes]]:
        """The cached path set for ``key``, or None (miss / failed
        verification — the latter with a warning; the caller recomputes
        and the next store overwrites the bad entry)."""
        art, man_path = self._paths(key)
        if not os.path.exists(art) or not os.path.exists(man_path):
            record_cache_event("walk", "miss")
            return None
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            warnings.warn(f"walk cache manifest {man_path} unreadable "
                          f"({e!r}); recomputing walks", RuntimeWarning)
            record_cache_event("walk", "verify_failed")
            return None
        if manifest.get("schema") != SCHEMA_VERSION \
                or manifest.get("key") != key:
            warnings.warn(
                f"walk cache entry {art} is stale (schema/key mismatch — "
                f"a truncated key collision or an older cache layout); "
                f"recomputing walks", RuntimeWarning)
            record_cache_event("walk", "verify_failed")
            return None
        actual = sha256_file(art)
        if actual != manifest.get("sha256"):
            warnings.warn(
                f"walk cache entry {art} failed sha256 verification "
                f"(manifest {str(manifest.get('sha256'))[:12]}... vs file "
                f"{actual[:12]}...) — corrupt or torn entry; recomputing "
                f"walks", RuntimeWarning)
            record_cache_event("walk", "verify_failed")
            return None
        try:
            with np.load(art) as z:
                rows = z["rows"]
        except Exception as e:  # noqa: BLE001 — any unreadable npz = miss
            warnings.warn(f"walk cache entry {art} unreadable ({e!r}); "
                          f"recomputing walks", RuntimeWarning)
            record_cache_event("walk", "verify_failed")
            return None
        record_cache_event("walk", "disk_hit")
        return {row.tobytes() for row in rows}

    def store(self, key: str, path_set: Set[bytes], n_genes: int,
              meta: Optional[Dict] = None) -> str:
        """Write ``path_set`` under ``key`` (atomic: tmp + rename, manifest
        last — a crash between the two leaves a manifest-less file that
        load() treats as a miss). Returns the artifact path."""
        os.makedirs(self.directory, exist_ok=True)
        art, man_path = self._paths(key)
        nbytes = (n_genes + 7) // 8
        rows = np.frombuffer(b"".join(sorted(path_set)), dtype=np.uint8)
        rows = rows.reshape(len(path_set), nbytes) if path_set \
            else np.zeros((0, nbytes), dtype=np.uint8)
        tmp = f"{art}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, rows=rows)
        os.replace(tmp, art)
        write_json_atomic(man_path, {
            "schema": SCHEMA_VERSION, "key": key,
            "sha256": sha256_file(art), "n_rows": int(rows.shape[0]),
            "n_genes": int(n_genes), **(meta or {})})
        # Fault seam: kind=corrupt flips bytes in the artifact AFTER the
        # manifest recorded the good hash — silent post-save bitrot, the
        # torn-write shape the verification exists for. (Corrupting
        # before the hash would give the bad bytes a matching manifest
        # and the cache would serve them as truth.)
        fault_point("walk_cache", path=art)
        record_cache_event("walk", "store")
        return art


@dataclasses.dataclass
class SharedWalkTier:
    """An in-process memo stacked ABOVE the on-disk :class:`WalkCache`.

    The batch engine (batch/engine.py) runs B manifest lanes in one
    process; lanes whose walk inputs coincide — a seed sweep that varies
    only train/k-means seeds shares BOTH groups' products, subsample
    lanes share nothing — must pay each distinct product once and split
    the bill. The memo holds this run's products by the same
    content-addressed key the disk tier uses, so sharing needs no byte
    verification (the object never left the process); the disk tier
    underneath still serves cross-run hits and receives every store.
    Accounting distinguishes the three outcomes (``memo_hits`` /
    ``disk_hits`` / ``walked``) so the bench A/B can attribute its
    speedup honestly.
    """

    disk: Optional[WalkCache] = None
    memo: Dict[str, Set[bytes]] = dataclasses.field(default_factory=dict)
    memo_hits: int = 0
    disk_hits: int = 0
    walked: int = 0

    def load(self, key: str) -> Optional[Set[bytes]]:
        hit = self.memo.get(key)
        if hit is not None:
            self.memo_hits += 1
            record_cache_event("walk", "memo_hit")
            return hit
        if self.disk is not None:
            hit = self.disk.load(key)
            if hit is not None:
                self.disk_hits += 1
                self.memo[key] = hit
                return hit
        else:
            record_cache_event("walk", "miss")
        return None

    def store(self, key: str, path_set: Set[bytes], n_genes: int,
              meta: Optional[Dict] = None) -> None:
        self.walked += 1
        self.memo[key] = path_set
        if self.disk is not None:
            self.disk.store(key, path_set, n_genes, meta=meta)

    def stats(self) -> Dict[str, int]:
        return {"memo_hits": self.memo_hits, "disk_hits": self.disk_hits,
                "walked": self.walked}


def autotune_cache_path(cache_dir: Optional[str]) -> Optional[str]:
    """The kernel-autotune tier's record file under ``--cache-dir``.

    A third tier beside xla/ and walks/: measured packed-kernel tile
    plans, keyed inside the file by exact problem shape + backend
    signature + kernel schema (ops/packed_matmul.py owns the format and
    its staleness rules — this helper only names the location, so every
    caller agrees on it). None when no cache root is configured: the
    sweep then runs in-memory only and repeat runs re-measure.
    """
    if not cache_dir:
        return None
    return os.path.join(cache_dir, "autotune", "packed_matmul.json")


def configure_xla_cache(xla_cache_dir: Optional[str]) -> None:
    """Point jax's persistent compilation cache at ``xla_cache_dir``.

    Extracted from the pipeline so the batch engine configures the tier
    identically (jax imported inside — this module stays importable with
    no backend). The reset dance: the persistent-cache object binds to
    whatever config the FIRST compile saw — a different dir, or
    (measured) NO dir at all — so enabling the cache after any uncached
    compile is a silent no-op and changing --cache-dir mid-process keeps
    writing the OLD location; reset so the next compile re-initializes
    against the dir just configured.
    """
    if not xla_cache_dir:
        return
    import jax

    global _configured_xla
    _configured_xla = xla_cache_dir
    prev_cache_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", xla_cache_dir)
    # Persist every program: a pipeline run compiles a bounded set of
    # programs, so cache-write cost is trivial next to ANY compile.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if prev_cache_dir != xla_cache_dir:
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API; cache staying
            pass           # stale only costs warm-run speed


def resolve_cache_tiers(cache_dir: Optional[str],
                        compilation_cache: Optional[str],
                        walk_cache_enabled: bool = True,
                        ) -> tuple:
    """(compilation_cache_dir | None, WalkCache | None) for a run's flags.

    ``--cache-dir`` implies both tiers under one root; each narrower
    control still works alone (``--compilation-cache`` overrides the xla
    tier's location, ``--no-walk-cache`` disables the artifact tier).
    The kernel-autotune tier rides the same root via
    :func:`autotune_cache_path`.
    """
    xla_dir = compilation_cache
    walks: Optional[WalkCache] = None
    if cache_dir:
        if not xla_dir:
            xla_dir = os.path.join(cache_dir, "xla")
        if walk_cache_enabled:
            walks = WalkCache(os.path.join(cache_dir, "walks"))
    return xla_dir, walks
