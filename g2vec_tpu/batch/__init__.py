"""Batched multi-cohort execution engine (see batch/engine.py)."""
from g2vec_tpu.batch.engine import (BatchResult, LaneVariant, ManifestError,
                                    lane_config, load_manifest,
                                    plan_variants, run_batch)

__all__ = ["BatchResult", "LaneVariant", "ManifestError", "lane_config",
           "load_manifest", "plan_variants", "run_batch"]
