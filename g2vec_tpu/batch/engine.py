"""Batched multi-cohort execution engine — runs/hour is the metric.

The paper's workflow is never one run: biomarker discovery is validated
by repeated runs over seeds and patient resamples, and after PR 3/4
closed the single-run rooflines, N runs still cost N x — serial stages,
re-paid compiles, the device idle between jobs. Throughput-first
embedding systems (GraphVite, arXiv:1903.00757; HUGE's TPU-resident
pipeline, arXiv:2307.14490) get their headline numbers by batching
independent work into one device program and amortizing everything
shared. This engine does that for whole pipeline runs:

- A **manifest** enumerates variants of one base config — seeds, k-means
  seeds, hyperparameters, patient subsamples (``--manifest`` JSON, or
  ``--seeds N`` for the canonical amortized seed sweep).
- The **lane planner** deduplicates everything content-identical across
  variants: stages 1-2 run once; each distinct (expression identity,
  group, walk seed) produces ONE stage-3 walk task on the PR 3 overlap
  pool (lanes sharing a product split the bill; the sha256 disk tier
  underneath still serves cross-run hits — cache.SharedWalkTier); each
  lane's integration runs as a pool task the moment its two walk
  products land.
- Lanes whose realized trainer shapes and hyperparameters coincide form
  a **shape bucket**, executed as ONE batched device program: the
  chunked while_loop trainer vmapped over a lane axis (params/opt-state
  ``[B, ...]``; per-lane early stop rides the select-mask machinery, so
  a finished lane freezes without recompiling anything —
  train/trainer.py ``train_cbow_lanes``). Bucket chunk programs warm
  CONCURRENTLY on the pool while earlier buckets train — B distinct
  shapes pay max(compile) wall, not sum.
- Stages 5-6 batch across ALL lanes regardless of trainer bucketing
  (the [B, genes, hidden] k-means stack is manifest-invariant):
  vmapped k-means / t-scores / minmax, host top-N at the writer
  boundary only (analysis.py lanes variants).

Contract: every lane's three output files are BITWISE the files the
same config produces through ``pipeline.run`` solo (float32, same
backend) — ``lane_config`` builds that solo config, and
tests/test_batch_engine.py holds the engine to it byte-for-byte.

Since the serve refactor, lane execution is split from process lifetime:
:class:`ResidentEngine` owns the warm state (walk-tier memo, overlap
pool, dataset memo, program caches) and accepts any number of
``execute`` calls; ``run_batch`` wraps one ephemeral instance for the
one-shot CLI, and ``serve/daemon.py`` keeps one alive for the daemon
lifetime (ARCHITECTURE.md §11).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from g2vec_tpu.config import G2VecConfig


class ManifestError(ValueError):
    """A malformed run manifest — names the offending variant and key."""


#: Per-variant override keys a manifest may set; anything else is a typo
#: the engine refuses to guess about.
_VARIANT_KEYS = ("name", "seed", "train_seed", "kmeans_seed",
                 "learningRate", "epoch", "patient_subsample",
                 "subsample_seed", "subsample_mode", "cv_folds", "cv_fold",
                 "permute_seed")
_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


@dataclasses.dataclass(frozen=True)
class LaneVariant:
    """One manifest lane: the variant axes over the base config."""

    index: int
    name: str
    seed: int
    train_seed: int
    kmeans_seed: int
    learningRate: float
    epoch: int
    patient_subsample: float
    subsample_seed: int
    subsample_mode: str = "fraction"
    cv_folds: int = 0
    cv_fold: int = 0
    permute_seed: Optional[int] = None

    def fingerprint(self) -> str:
        payload = json.dumps({k: getattr(self, k) for k in _VARIANT_KEYS},
                             sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:8]

    def tag(self) -> str:
        """The metrics ``lane`` field: manifest index + variant
        fingerprint (utils/metrics.py bind_lane)."""
        return f"{self.index}:{self.fingerprint()}"

    def expr_key(self) -> Optional[Tuple]:
        """Expression identity: lanes sharing it see byte-identical
        expression matrices (None = the full un-subsampled data).

        ``permute_seed`` is deliberately NOT part of the key: a
        permutation null shuffles labels for stage-6 scoring only, so
        every null lane over one cohort shares that cohort's expression
        — and therefore its graphs and walk products."""
        if self.subsample_mode == "bootstrap":
            return ("bootstrap", self.patient_subsample,
                    self.subsample_seed)
        if self.subsample_mode == "fold":
            return ("fold", self.cv_folds, self.cv_fold,
                    self.subsample_seed)
        if not self.patient_subsample:
            return None
        return (self.patient_subsample, self.subsample_seed)


def _variant_from_dict(index: int, obj, cfg: G2VecConfig,
                       origin: Optional[str] = None) -> LaneVariant:
    """Validate one variant object. ``origin`` names WHERE the variant
    came from when it was generated rather than hand-written — a
    scenario-expanded replicate reports "manifest variant 3 (scenario
    ab12cd, replicate 3)", not just its position in a list the user
    never wrote."""
    who = f"manifest variant {index}" + (f" ({origin})" if origin else "")
    if not isinstance(obj, dict):
        raise ManifestError(
            f"{who} must be an object, got {type(obj).__name__}")
    unknown = sorted(set(obj) - set(_VARIANT_KEYS))
    if unknown:
        raise ManifestError(
            f"{who} has unknown key(s) {unknown}; "
            f"allowed: {sorted(_VARIANT_KEYS)}")

    def _int(k, default, lo=0):
        v = obj.get(k, default)
        if not isinstance(v, int) or isinstance(v, bool) or v < lo:
            raise ManifestError(
                f"{who}: {k!r} must be an int >= {lo}, got {v!r}")
        return v

    lr = obj.get("learningRate", cfg.learningRate)
    if not isinstance(lr, (int, float)) or isinstance(lr, bool) or lr <= 0:
        raise ManifestError(
            f"{who}: 'learningRate' must be > 0, got {lr!r}")
    sub = obj.get("patient_subsample", cfg.patient_subsample)
    if not isinstance(sub, (int, float)) or isinstance(sub, bool) \
            or not (0.0 <= float(sub) <= 1.0):
        raise ManifestError(
            f"{who}: 'patient_subsample' must be 0 "
            f"(off) or in (0,1], got {sub!r}")
    mode = obj.get("subsample_mode", cfg.subsample_mode)
    if mode not in ("fraction", "bootstrap", "fold"):
        raise ManifestError(
            f"{who}: 'subsample_mode' must be "
            f"fraction|bootstrap|fold, got {mode!r}")
    cv_folds = _int("cv_folds", cfg.cv_folds)
    cv_fold = _int("cv_fold", cfg.cv_fold)
    if mode == "fold":
        if cv_folds < 2:
            raise ManifestError(
                f"{who}: subsample_mode 'fold' needs 'cv_folds' >= 2, "
                f"got {cv_folds}")
        if cv_fold >= cv_folds:
            raise ManifestError(
                f"{who}: 'cv_fold' must be in [0, {cv_folds}), "
                f"got {cv_fold}")
        if float(sub):
            raise ManifestError(
                f"{who}: subsample_mode 'fold' derives the cohort from "
                f"the fold partition; 'patient_subsample' must be 0")
    elif cv_folds or cv_fold:
        raise ManifestError(
            f"{who}: 'cv_folds'/'cv_fold' are only meaningful with "
            f"subsample_mode 'fold'")
    pseed = obj.get("permute_seed", cfg.permute_seed)
    if pseed is not None and (not isinstance(pseed, int)
                              or isinstance(pseed, bool) or pseed < 0):
        raise ManifestError(
            f"{who}: 'permute_seed' must be null or an int >= 0, "
            f"got {pseed!r}")
    name = obj.get("name", f"lane{index}")
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ManifestError(
            f"{who}: 'name' must match {_NAME_RE.pattern}, got {name!r}")
    seed = _int("seed", cfg.seed)
    return LaneVariant(
        index=index, name=name, seed=seed,
        train_seed=_int("train_seed",
                        cfg.train_seed if cfg.train_seed is not None
                        else seed),
        kmeans_seed=_int("kmeans_seed", cfg.kmeans_seed),
        learningRate=float(lr),
        epoch=_int("epoch", cfg.epoch, lo=1),
        patient_subsample=float(sub),
        subsample_seed=_int("subsample_seed", cfg.subsample_seed),
        subsample_mode=mode, cv_folds=cv_folds, cv_fold=cv_fold,
        permute_seed=pseed)


def load_manifest(path: str, cfg: G2VecConfig) -> List[LaneVariant]:
    """Parse + validate a JSON manifest against the base config.

    Format: a JSON LIST of variant objects (keys: ``_VARIANT_KEYS``;
    every key optional, defaults come from the base config). Validation
    failures raise :class:`ManifestError` naming the variant index and
    key — a manifest typo must die before any walk samples.
    """
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ManifestError(f"cannot read manifest {path!r}: {e}") from e
    except ValueError as e:
        raise ManifestError(f"manifest {path!r} is not valid JSON: {e}") from e
    if not isinstance(doc, list) or not doc:
        raise ManifestError(
            f"manifest {path!r} must be a non-empty JSON list of variant "
            f"objects, got {type(doc).__name__}")
    variants = [_variant_from_dict(i, obj, cfg) for i, obj in enumerate(doc)]
    names = [v.name for v in variants]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ManifestError(
            f"manifest {path!r} has duplicate variant name(s) {dupes} — "
            f"lane outputs would overwrite each other")
    return variants


def seed_sweep_variants(cfg: G2VecConfig, n: int) -> List[LaneVariant]:
    """The canonical amortized seed sweep (``--seeds N``): train/k-means
    seeds vary per lane, the WALK seed stays the base config's — all N
    lanes share one stage-3 product and re-train under fresh splits and
    inits (the validation protocol's repeat-runs axis)."""
    base_train = cfg.train_seed if cfg.train_seed is not None else cfg.seed
    return [_variant_from_dict(
        k, {"name": f"s{k}", "train_seed": base_train + k,
            "kmeans_seed": cfg.kmeans_seed + k}, cfg)
        for k in range(n)]


def plan_variants(cfg: G2VecConfig) -> List[LaneVariant]:
    """The run's lane list from whichever batch flag is set."""
    if cfg.manifest and cfg.batch_seeds:
        raise ManifestError("--manifest and --seeds are mutually exclusive")
    if cfg.manifest:
        return load_manifest(cfg.manifest, cfg)
    if cfg.batch_seeds:
        return seed_sweep_variants(cfg, cfg.batch_seeds)
    raise ManifestError("batch engine needs --manifest or --seeds")


def lane_config(cfg: G2VecConfig, v: LaneVariant) -> G2VecConfig:
    """The SOLO config equivalent to lane ``v`` — the parity contract's
    other side: ``pipeline.run(lane_config(cfg, v))`` must produce
    byte-identical outputs to the engine's lane."""
    lane = dataclasses.replace(
        cfg, seed=v.seed, train_seed=v.train_seed,
        kmeans_seed=v.kmeans_seed, learningRate=v.learningRate,
        epoch=v.epoch, patient_subsample=v.patient_subsample,
        subsample_seed=v.subsample_seed,
        subsample_mode=v.subsample_mode, cv_folds=v.cv_folds,
        cv_fold=v.cv_fold, permute_seed=v.permute_seed,
        result_name=f"{cfg.result_name}.{v.name}",
        manifest=None, batch_seeds=0, metrics_jsonl=None,
        scenario=None, replicates=0, folds=0)
    lane.validate()
    return lane


def _lane_cohort(data, v: LaneVariant):
    """The variant's patient cohort — the same derivation ``pipeline.run``
    applies solo at stage 2, so the PR 5 byte-parity contract extends to
    the bootstrap/fold cohort axes unchanged."""
    from g2vec_tpu.preprocess import fold_cohort, subsample_patients

    if v.subsample_mode == "bootstrap":
        return subsample_patients(data, v.patient_subsample or 1.0,
                                  v.subsample_seed, with_replacement=True)
    if v.subsample_mode == "fold":
        return fold_cohort(data, v.cv_folds, v.cv_fold, v.subsample_seed)
    return subsample_patients(data, v.patient_subsample, v.subsample_seed)


@dataclasses.dataclass
class BatchResult:
    """All lanes' results plus the batch-level attribution."""

    lanes: List                       # per-lane pipeline.PipelineResult
    variants: List[LaneVariant]
    wall_seconds: float
    runs_per_hour: float
    walk_stats: Dict[str, int]        # memo_hits / disk_hits / walked
    buckets: List[Dict]               # per-bucket {n_paths, lanes, mode}
    stage_seconds: Dict[str, float]


def run_batch(cfg: G2VecConfig,
              console: Callable[[str], None] = print) -> BatchResult:
    """Plan the manifest into lanes and execute them batched — the one-shot
    CLI shape: an ephemeral :class:`ResidentEngine` is built from the
    config, executes the manifest, and is torn down with the process."""
    cfg.validate()
    variants = plan_variants(cfg)
    with ResidentEngine(cache_dir=cfg.cache_dir,
                        compilation_cache=cfg.compilation_cache,
                        walk_cache=cfg.walk_cache) as engine:
        return engine.execute(cfg, variants, console=console)


class ResidentEngine:
    """The lane execution core with its warm state split OUT of the process
    lifetime.

    ``run_batch`` used to own everything — caches, pool, data, device
    programs — for exactly one manifest, so every invocation re-paid
    startup, loads, and compiles. This class holds the warm inventory and
    accepts any number of :meth:`execute` calls against it:

    - the **SharedWalkTier memo** (cache.py): walk products stay resident,
      so a later job over the same cohort/seed shares stage 3 in-process;
    - the **overlap pool** (parallel/overlap.py): one executor for walk
      tasks and background compile warms across all batches (per-batch
      task-name prefixes + :meth:`OverlapScheduler.prune` keep it bounded);
    - the **dataset memo**: loaded + preprocessed inputs keyed by file
      identity (path, mtime, size), so repeat jobs skip stages 1-2;
    - the **program caches**: jit/LRU chunk programs and the persistent
      XLA tier are process-level — keeping the process alive is what makes
      them warm; this class is why a process worth keeping alive exists.

    ``serve/daemon.py`` keeps ONE instance for the daemon lifetime; the
    engine itself knows nothing about sockets, queues, or jobs beyond the
    optional per-lane ``lane_jobs`` metrics attribution.
    """

    def __init__(self, *, cache_dir: Optional[str] = None,
                 compilation_cache: Optional[str] = None,
                 walk_cache: bool = True, max_workers: int = 8,
                 dataset_cap: int = 4):
        from collections import OrderedDict

        from g2vec_tpu.cache import SharedWalkTier, resolve_cache_tiers
        from g2vec_tpu.parallel.overlap import OverlapScheduler

        xla_dir, disk_walk_cache = resolve_cache_tiers(
            cache_dir, compilation_cache, walk_cache)
        self._xla_cache_dir = xla_dir
        self.walk_tier = SharedWalkTier(disk=disk_walk_cache)
        self.overlap = OverlapScheduler(max_workers=max_workers)
        self._datasets: "OrderedDict" = OrderedDict()
        self._dataset_cap = dataset_cap
        self._serial = 0
        self.batches_executed = 0
        self.lanes_executed = 0
        self.warm_shapes: List[Dict] = []

    def execute(self, cfg: G2VecConfig,
                variants: Optional[List[LaneVariant]] = None, *,
                console: Callable[[str], None] = print,
                metrics=None,
                lane_jobs: Optional[List[str]] = None,
                check: Optional[Callable[[], None]] = None,
                lifecycle=None) -> BatchResult:
        """Run ``variants`` (default: plan from ``cfg``) as one batch on
        this engine's warm state. ``metrics`` may be a caller-owned
        MetricsWriter/BoundMetrics view (the daemon's lifetime stream);
        None builds one from ``cfg.metrics_jsonl`` for this call.
        ``lane_jobs`` stamps lane i's events with ``job_id`` so joined
        jobs stay attributable (utils/metrics.py ``bind_job``).

        ``check`` is the cooperative-interruption hook threaded into the
        trainers (resilience/lifecycle.py); ``lifecycle(job_id, state,
        info)`` observes per-job durable transitions ("checkpointed",
        "resumed") — job_id comes from ``lane_jobs`` (lane tag when
        absent)."""
        return _execute_lanes(self, cfg, variants, console=console,
                              metrics=metrics, lane_jobs=lane_jobs,
                              check=check, lifecycle=lifecycle)

    def status(self) -> Dict:
        """The warm-state inventory (the serve /status currency)."""
        from g2vec_tpu.train.stream import stream_stats

        return {
            "batches_executed": self.batches_executed,
            "lanes_executed": self.lanes_executed,
            "datasets_resident": len(self._datasets),
            "walk_tier": self.walk_tier.stats(),
            "walk_products_resident": len(self.walk_tier.memo),
            "warm_shapes": [dict(s) for s in self.warm_shapes],
            # Streaming-job totals (shards emitted, ring high-water,
            # prefetch wait, last time-to-first-update) — empty dict
            # until the first --train-mode streaming job runs.
            "stream": stream_stats(),
        }

    def _dataset_key(self, cfg: G2VecConfig) -> Tuple:
        def ident(path):
            st = os.stat(path)
            return (os.path.abspath(path), st.st_mtime_ns, st.st_size)
        return (ident(cfg.expression_file), ident(cfg.clinical_file),
                ident(cfg.network_file), cfg.use_native_io)

    def dataset(self, cfg: G2VecConfig) -> Tuple[Dict, bool]:
        """The loaded + preprocessed bundle for ``cfg``'s input files,
        memoized on file identity (path, mtime_ns, size — an edited input
        re-loads instead of silently serving stale genes). Returns
        ``(bundle, was_resident)``."""
        from g2vec_tpu.io.readers import (load_clinical, load_expression,
                                          load_network)
        from g2vec_tpu.preprocess import (edges_to_indices,
                                          find_common_genes, make_gene2idx,
                                          match_labels, restrict_data,
                                          restrict_network)

        key = self._dataset_key(cfg)
        hit = self._datasets.get(key)
        if hit is not None:
            self._datasets.move_to_end(key)
            return hit, True
        data = load_expression(cfg.expression_file,
                               use_native=cfg.use_native_io)
        clinical = load_clinical(cfg.clinical_file)
        network = load_network(cfg.network_file)
        data.label = match_labels(clinical, data.sample)
        common = find_common_genes(network.genes, data.gene)
        network = restrict_network(network, common)
        data = restrict_data(data, common)
        gene2idx = make_gene2idx(data.gene)
        src, dst = edges_to_indices(network, gene2idx)
        bundle = {"data": data, "src": src, "dst": dst,
                  "n_genes": int(data.expr.shape[1]),
                  "n_edges": len(network.edges)}
        self._datasets[key] = bundle
        while len(self._datasets) > self._dataset_cap:
            self._datasets.popitem(last=False)
        return bundle, False

    def close(self) -> None:
        self.overlap.close()

    def __enter__(self) -> "ResidentEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _execute_streaming(engine: ResidentEngine, cfg: G2VecConfig,
                       variants: Optional[List[LaneVariant]], *,
                       console: Callable[[str], None],
                       metrics, lane_jobs: Optional[List[str]],
                       check: Optional[Callable[[], None]] = None,
                       lifecycle=None) -> BatchResult:
    """Streaming-mode lanes: each variant runs the SOLO streaming
    pipeline, sequentially.

    The vmapped lane trainer wants every lane's full path matrix on
    device at once — exactly the materialization ``--train-mode
    streaming`` exists to avoid — so streaming jobs trade lane batching
    for the mode's own overlap (sampling ∥ training) and its bounded
    memory. This keeps streaming jobs first-class under the batch CLI
    and the serve daemon (admission, journaling, metrics attribution all
    unchanged); a tenant who wants lane-batched throughput on small
    cohorts uses train_mode=full, one who wants a big graph streams.
    """
    from g2vec_tpu.pipeline import run as run_pipeline
    from g2vec_tpu.train.stream import stream_stats
    from g2vec_tpu.utils.metrics import MetricsWriter

    cfg.validate()
    if variants is None:
        variants = plan_variants(cfg)
    n_lanes = len(variants)
    if lane_jobs is not None and len(lane_jobs) != n_lanes:
        raise ValueError(f"lane_jobs has {len(lane_jobs)} entries for "
                         f"{n_lanes} lane(s)")
    own_metrics = None
    if metrics is None:
        own_metrics = metrics = MetricsWriter(cfg.metrics_jsonl)
    t_start = time.time()
    parent = os.path.dirname(cfg.result_name)
    if parent:
        os.makedirs(parent, exist_ok=True)
    console(f">>> [batch] streaming mode: {n_lanes} lane(s), each the solo "
            f"streaming pipeline (no lane batching — the path matrix "
            f"never materializes)")
    results: List = []
    try:
        for i, v in enumerate(variants):
            lm = (metrics.bind_job(lane_jobs[i]).bind_lane(v.tag())
                  if lane_jobs is not None else metrics.bind_lane(v.tag()))
            lm.emit("lane_variant", **dataclasses.asdict(v))
            lane_cfg = lane_config(cfg, v)
            if cfg.checkpoint_dir:
                # Per-lane cursor directory: the variant name is stable
                # across restarts (the daemon names lanes
                # "<job_id>.<variant>"), so a relaunched job resumes its
                # own cursor and never reads a sibling's.
                lane_cfg = dataclasses.replace(
                    lane_cfg,
                    checkpoint_dir=os.path.join(cfg.checkpoint_dir, v.name),
                    resume=cfg.resume)
            jid = lane_jobs[i] if lane_jobs is not None else v.tag()
            lane_lifecycle = (
                (lambda state, info, _jid=jid:
                 lifecycle(_jid, state, info))
                if lifecycle is not None else None)
            res = run_pipeline(lane_cfg, console=console, check=check,
                               lifecycle=lane_lifecycle)
            lm.emit("stream", **res.stream_stats)
            lm.emit("done", outputs=res.output_files, acc_val=res.acc_val,
                    n_paths=res.n_paths)
            results.append(res)
        wall = time.time() - t_start
        rph = n_lanes / wall * 3600.0
        metrics.emit("done", n_lanes=n_lanes, wall_seconds=round(wall, 3),
                     runs_per_hour=round(rph, 2), train_mode="streaming",
                     stream_totals=stream_stats())
        engine.batches_executed += 1
        engine.lanes_executed += n_lanes
        return BatchResult(
            lanes=results, variants=variants, wall_seconds=wall,
            runs_per_hour=rph, walk_stats={},
            buckets=[{"n_paths": r.n_paths, "lanes": 1,
                      "mode": "stream-solo"} for r in results],
            stage_seconds={})
    finally:
        if own_metrics is not None:
            own_metrics.close()


def _execute_lanes(engine: ResidentEngine, cfg: G2VecConfig,
                   variants: Optional[List[LaneVariant]], *,
                   console: Callable[[str], None],
                   metrics, lane_jobs: Optional[List[str]],
                   check: Optional[Callable[[], None]] = None,
                   lifecycle=None) -> BatchResult:
    if cfg.train_mode == "streaming":
        return _execute_streaming(engine, cfg, variants, console=console,
                                  metrics=metrics, lane_jobs=lane_jobs,
                                  check=check, lifecycle=lifecycle)
    import jax

    from g2vec_tpu.analysis import (biomarker_scores_lanes, freq_index,
                                    find_lgroups_lanes, top_biomarkers,
                                    warm_lgroups_compile)
    from g2vec_tpu.cache import (NATIVE_FAMILY, configure_xla_cache,
                                 walk_cache_key)
    from g2vec_tpu.io.writers import (write_biomarkers, write_lgroups,
                                      write_vectors)
    from g2vec_tpu.ops.backend import resolve_walker_backend
    from g2vec_tpu.ops.graph import thresholded_edges
    from g2vec_tpu.ops.host_walker import resolve_sampler_threads
    from g2vec_tpu.ops.walker import count_gene_freq, integrate_path_sets
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.pipeline import PipelineResult, _background_warm
    from g2vec_tpu.preprocess import permute_labels
    from g2vec_tpu.resilience.faults import fault_point, install_plan
    from g2vec_tpu.train.trainer import (LaneTrainSpec, train_cbow,
                                         train_cbow_lanes,
                                         warm_train_compile)
    from g2vec_tpu.utils.metrics import MetricsWriter
    from g2vec_tpu.utils.timing import StageTimer
    import jax.numpy as jnp

    cfg.validate()
    if variants is None:
        variants = plan_variants(cfg)
    n_lanes = len(variants)
    if lane_jobs is not None and len(lane_jobs) != n_lanes:
        raise ValueError(f"lane_jobs has {len(lane_jobs)} entries for "
                         f"{n_lanes} lane(s)")
    if cfg.fault_plan:
        install_plan(cfg.fault_plan)
    configure_xla_cache(engine._xla_cache_dir)
    walk_tier = engine.walk_tier
    tier_stats0 = walk_tier.stats()
    engine._serial += 1
    pfx = f"b{engine._serial}:"       # per-batch overlap task namespace

    # A manifest run fans one result_name into 3N files — create the
    # parent dirs up front (the metrics stream opens before stage 7).
    for parent in {os.path.dirname(cfg.result_name),
                   os.path.dirname(cfg.metrics_jsonl or "")}:
        if parent:
            os.makedirs(parent, exist_ok=True)
    timer = StageTimer()
    own_metrics = None
    if metrics is None:
        own_metrics = metrics = MetricsWriter(cfg.metrics_jsonl)
    if lane_jobs is not None:
        lane_metrics = [metrics.bind_job(lane_jobs[i]).bind_lane(v.tag())
                        for i, v in enumerate(variants)]
    else:
        lane_metrics = [metrics.bind_lane(v.tag()) for v in variants]
    t_start = time.time()

    console(">>> [batch] 0. Manifest")
    console(f"    {n_lanes} lane(s) over base config "
            f"{os.path.basename(cfg.expression_file)!r}; "
            f"lanes/bucket cap {cfg.lanes}")
    metrics.emit("batch_config", n_lanes=n_lanes, lanes_cap=cfg.lanes,
                 batch_serial=engine._serial,
                 variants=[dataclasses.asdict(v) for v in variants])
    for v, lm in zip(variants, lane_metrics):
        lm.emit("lane_variant", **dataclasses.asdict(v))

    overlap = engine.overlap
    try:
        console(">>> [batch] 1-2. Load + preprocess (shared, resident)")
        fault_point("load")
        fault_point("preprocess")
        with timer.stage("load"):
            bundle, was_resident = engine.dataset(cfg)
        data, src, dst = bundle["data"], bundle["src"], bundle["dst"]
        n_genes, n_edges = bundle["n_genes"], bundle["n_edges"]
        if was_resident:
            console("    dataset resident (stages 1-2 served from memo)")
        console(f"    n_genes {n_genes}, n_edges {n_edges}, "
                f"n_samples {data.expr.shape[0]} (base)")

        # Per-lane expression identity (subsample lanes fork rows; the
        # gene axis — and therefore every device shape downstream of it —
        # is manifest-invariant).
        lane_data = {}
        for v in variants:
            ek = v.expr_key()
            if ek not in lane_data:
                lane_data[ek] = data if ek is None else _lane_cohort(data, v)

        walker_backend = resolve_walker_backend(cfg)
        sampler_threads = (resolve_sampler_threads(cfg.sampler_threads)
                           if walker_backend == "native" else 0)
        mesh_ctx = make_mesh_context(cfg.mesh_shape)

        # Stage-5's batched shape is known NOW — warm the vmapped k-means
        # before any walk finishes (it hides under stages 3-4 entirely).
        warm_kmeans_lanes = min(n_lanes, cfg.lanes)
        overlap.submit(pfx + "warm_lgroups", _background_warm(
            lambda: warm_lgroups_compile(
                n_genes, cfg.sizeHiddenlayer, k=cfg.n_lgroups,
                iters=cfg.kmeans_iters,
                lanes=warm_kmeans_lanes if n_lanes > 1 else 0), console))

        console(">>> [batch] 3. Plan + sample walks (amortized)")
        fault_point("paths")
        # ---- walk planning: one task per distinct product ----
        edges_memo: Dict = {}          # (expr_key, group) -> (s, d, w)
        walk_of_key: Dict[str, str] = {}      # cache key -> task name
        lane_walks: List[List[str]] = [[] for _ in range(n_lanes)]
        share_count: Dict[str, int] = {}
        with timer.stage("walk_plan"):
            for li, v in enumerate(variants):
                ldata = lane_data[v.expr_key()]
                for gi, group in enumerate(["g", "p"]):
                    ekey = (v.expr_key(), gi)
                    if ekey not in edges_memo:
                        expr_group = ldata.expr[ldata.label == gi]
                        edges_memo[ekey] = thresholded_edges(
                            expr_group, src, dst,
                            threshold=cfg.pcc_threshold)
                    s_k, d_k, w_k = edges_memo[ekey]
                    ckey = walk_cache_key(
                        np.asarray(s_k), np.asarray(d_k), np.asarray(w_k),
                        n_genes, len_path=cfg.lenPath,
                        reps=cfg.numRepetition, seed=(v.seed << 1) | gi,
                        # One family for BOTH backends: the device
                        # sampler is bit-exact with the native one
                        # (cache.py NATIVE_FAMILY contract), so lanes
                        # share walk products across backends too.
                        family=NATIVE_FAMILY)
                    if ckey not in walk_of_key:
                        task = f"{pfx}walk:{group}:{ckey[:12]}"
                        walk_of_key[ckey] = task
                        share_count[task] = 0
                        overlap.submit(task, _make_walk_task(
                            cfg, np.asarray(s_k), np.asarray(d_k),
                            np.asarray(w_k), n_genes,
                            seed=(v.seed << 1) | gi,
                            backend=walker_backend, tier=walk_tier,
                            ckey=ckey, group=group))
                    share_count[walk_of_key[ckey]] += 1
                    lane_walks[li].append(walk_of_key[ckey])
        n_walk_tasks = len(walk_of_key)
        console(f"    {2 * n_lanes} lane-walks -> {n_walk_tasks} distinct "
                f"product(s) on the pool "
                f"({walker_backend}, {sampler_threads} sampler thread(s))")

        # ---- per-lane integration, as walks land ----
        def _integrate(li: int):
            def fn():
                ps = [overlap.result(n) for n in lane_walks[li]]
                paths, labels = integrate_path_sets(ps[0], ps[1], n_genes,
                                                    packed=True)
                if paths.shape[0] < 2:
                    raise ValueError(
                        f"lane {variants[li].name!r}: fewer than 2 distinct "
                        f"group-specific paths — the |PCC| > "
                        f"{cfg.pcc_threshold:.2f} graphs are too sparse; "
                        f"lower --pcc-threshold or raise -r")
                gene_freq = count_gene_freq(paths, labels, data.gene,
                                            packed=True)
                return paths, labels, gene_freq
            return fn

        for li in range(n_lanes):
            overlap.submit(f"{pfx}integrate:{li}", _integrate(li),
                           deps=lane_walks[li])

        payloads: List = [None] * n_lanes
        with timer.stage("paths"):
            for name, result in overlap.as_completed(
                    [f"{pfx}integrate:{li}" for li in range(n_lanes)]):
                li = int(name.rsplit(":", 1)[1])
                payloads[li] = result
                paths, labels, gene_freq = result
                lane_metrics[li].emit(
                    "paths", n_paths=int(paths.shape[0]),
                    n_path_genes=len(gene_freq),
                    walker_backend=walker_backend,
                    sampler_threads=sampler_threads)
        # Per-batch deltas: the tier is engine-resident, so its raw
        # counters span every batch this process has run.
        walk_stats = {k: v - tier_stats0[k]
                      for k, v in walk_tier.stats().items()}
        # Task-level dedup (lanes pointing at one product) is the third
        # share tier: lane_shared counts lane-walks served by another
        # lane's task, on top of the tier's memo/disk hits.
        walk_stats["lane_shared"] = 2 * n_lanes - n_walk_tasks
        metrics.emit("batch_walks", n_walk_tasks=n_walk_tasks,
                     lane_walks=2 * n_lanes, **walk_stats)

        # ---- shape buckets ----
        console(">>> [batch] 4. Train (shape-bucketed lanes)")
        fault_point("train")
        buckets: Dict[Tuple, List[int]] = {}
        for li, v in enumerate(variants):
            bkey = (payloads[li][0].shape, v.learningRate, v.epoch)
            buckets.setdefault(bkey, []).append(li)
        # Deterministic order, capped chunks. A meshed run pins every
        # bucket to the solo trainer (the vmapped lane program is
        # single-device by contract — train_cbow_lanes docstring).
        lane_cap = 1 if cfg.mesh_shape else cfg.lanes
        bucket_list: List[Tuple[Tuple, List[int]]] = []
        for bkey in sorted(buckets, key=lambda k: min(buckets[k])):
            lis = sorted(buckets[bkey])
            for lo in range(0, len(lis), lane_cap):
                bucket_list.append((bkey, lis[lo:lo + lane_cap]))
        console("    " + ", ".join(
            f"bucket[{i}]: {len(lis)} lane(s) @ n_paths={bkey[0][0]}"
            for i, (bkey, lis) in enumerate(bucket_list)))

        # Warm every bucket's chunk program CONCURRENTLY on the pool: B
        # distinct shapes pay max(compile) wall, not sum — the first
        # bucket joins its warm immediately, later buckets' compiles hide
        # under earlier buckets' training.
        for bi, (bkey, lis) in enumerate(bucket_list):
            shape, lr, epochs = bkey
            n_paths_b = int(shape[0])
            wshape = {"n_paths": n_paths_b, "lanes": len(lis),
                      "hidden": cfg.sizeHiddenlayer, "learning_rate": lr,
                      "max_epochs": epochs}
            if wshape not in engine.warm_shapes:
                engine.warm_shapes.append(wshape)
            overlap.submit(f"{pfx}warm_bucket:{bi}", _background_warm(
                lambda n=n_paths_b, lr=lr, e=epochs, B=len(lis):
                warm_train_compile(
                    n, n_genes, hidden=cfg.sizeHiddenlayer,
                    learning_rate=lr, max_epochs=e,
                    val_fraction=cfg.val_fraction,
                    decision_threshold=cfg.decision_threshold,
                    compute_dtype=cfg.compute_dtype,
                    param_dtype=cfg.param_dtype,
                    fused_eval=cfg.fused_eval,
                    epoch_superstep=cfg.epoch_superstep,
                    donate=cfg.donate_state,
                    lanes=B if B > 1 else 0), console))

        lane_results: List = [None] * n_lanes
        lane_emb: List = [None] * n_lanes     # device [G, hidden] each
        bucket_report = []
        with timer.stage("train"):
            for bi, (bkey, lis) in enumerate(bucket_list):
                shape, lr, epochs = bkey
                join_warm = (lambda bi=bi:
                             overlap.result(f"{pfx}warm_bucket:{bi}"))
                if len(lis) == 1:
                    li = lis[0]
                    v = variants[li]
                    paths, labels, _ = payloads[li]
                    lm = lane_metrics[li]
                    res = train_cbow(
                        paths, labels, packed_genes=n_genes,
                        hidden=cfg.sizeHiddenlayer, learning_rate=lr,
                        max_epochs=epochs, val_fraction=cfg.val_fraction,
                        decision_threshold=cfg.decision_threshold,
                        compute_dtype=cfg.compute_dtype,
                        param_dtype=cfg.param_dtype, seed=v.train_seed,
                        mesh_ctx=mesh_ctx,
                        on_epoch=lambda s, av, at, secs, lm=lm: lm.emit(
                            "epoch", step=s, acc_val=av, acc_tr=at,
                            secs=secs),
                        fused_eval=cfg.fused_eval,
                        epoch_superstep=cfg.epoch_superstep,
                        donate=cfg.donate_state,
                        pre_compile_hook=join_warm,
                        check=check)
                    lane_results[li] = res
                    if res.params is not None:
                        lane_emb[li] = res.params.w_ih.astype(
                            jnp.float32)[:n_genes]
                    else:
                        lane_emb[li] = res.w_ih
                    mode = "solo"
                else:
                    specs = [LaneTrainSpec(paths=payloads[li][0],
                                           labels=payloads[li][1],
                                           seed=variants[li].train_seed)
                             for li in lis]

                    def on_epoch(lane_b, s, av, at, secs, lis=lis):
                        lane_metrics[lis[lane_b]].emit(
                            "epoch", step=s, acc_val=av, acc_tr=at,
                            secs=secs)

                    results, emb_stack = train_cbow_lanes(
                        specs, packed_genes=n_genes,
                        hidden=cfg.sizeHiddenlayer, learning_rate=lr,
                        max_epochs=epochs, val_fraction=cfg.val_fraction,
                        decision_threshold=cfg.decision_threshold,
                        compute_dtype=cfg.compute_dtype,
                        param_dtype=cfg.param_dtype, on_epoch=on_epoch,
                        fused_eval=cfg.fused_eval,
                        epoch_superstep=cfg.epoch_superstep,
                        donate=cfg.donate_state,
                        pre_compile_hook=join_warm,
                        check=check)
                    for b, li in enumerate(lis):
                        lane_results[li] = results[b]
                        lane_emb[li] = emb_stack[b]
                    mode = "vmap"
                bucket_report.append({"n_paths": int(shape[0]),
                                      "lanes": len(lis), "mode": mode,
                                      "learning_rate": lr,
                                      "max_epochs": epochs})
                for li in lis:
                    r = lane_results[li]
                    lane_metrics[li].emit(
                        "train_done", stop_epoch=r.stop_epoch,
                        acc_val=r.acc_val, acc_tr=r.acc_tr,
                        stopped_early=r.stopped_early, bucket=bi,
                        bucket_mode=mode)
                    console(f"    [lane {variants[li].name}] "
                            f"stop epoch {r.stop_epoch:3d}  "
                            f"ACC[val]={r.acc_val:.4f}  "
                            f"ACC[tr]={r.acc_tr:.4f}"
                            + ("  (early)" if r.stopped_early else ""))

        console(">>> [batch] 5. Find L-groups (vmapped across lanes)")
        fault_point("lgroups")
        overlap.result(pfx + "warm_lgroups")
        freq_stack = np.stack([freq_index(data.gene, payloads[li][2])
                               for li in range(n_lanes)])
        lgroup_host = [None] * n_lanes
        lg_dev: List = [None] * n_lanes
        km_centers: List = [None] * n_lanes   # per-lane [k, H] ANN seeds
        with timer.stage("lgroups"):
            for lo in range(0, n_lanes, cfg.lanes):
                idx = list(range(lo, min(lo + cfg.lanes, n_lanes)))
                if len(idx) == 1 and n_lanes == 1:
                    from g2vec_tpu.analysis import find_lgroups_device

                    lg, kc = find_lgroups_device(
                        lane_emb[idx[0]], freq_stack[idx[0]],
                        key=jax.random.key(variants[idx[0]].kmeans_seed),
                        k=cfg.n_lgroups,
                        compat_tiebreak=cfg.compat_lgroup_tiebreak,
                        iters=cfg.kmeans_iters, return_centers=True)
                    lg_dev[idx[0]] = lg
                    km_centers[idx[0]] = np.asarray(kc, dtype=np.float32)
                    continue
                stack = jnp.stack([lane_emb[li] for li in idx])
                lg, kc = find_lgroups_lanes(
                    stack, freq_stack[idx],
                    [variants[li].kmeans_seed for li in idx],
                    k=cfg.n_lgroups,
                    compat_tiebreak=cfg.compat_lgroup_tiebreak,
                    iters=cfg.kmeans_iters, return_centers=True)
                kc_host = np.asarray(kc, dtype=np.float32)
                for b, li in enumerate(idx):
                    lg_dev[li] = lg[b]
                    km_centers[li] = kc_host[b]

        console(">>> [batch] 6. Select biomarkers (vmapped per cohort)")
        fault_point("biomarkers")
        scores_host = [None] * n_lanes
        with timer.stage("biomarkers"):
            # Scoring cohorts group on (expression identity, label view):
            # a permutation-null lane shares the cohort's walks/graphs but
            # scores against ITS shuffled labels, so it gets its own
            # t-score group (pipeline.py applies the same view solo).
            by_expr: Dict = {}
            for li, v in enumerate(variants):
                by_expr.setdefault((v.expr_key(), v.permute_seed),
                                   []).append(li)
            for (ek, pseed), lis in by_expr.items():
                ldata = lane_data[ek]
                labels = (ldata.label if pseed is None
                          else permute_labels(ldata.label, pseed))
                expr_good = ldata.expr[labels == 0]
                expr_poor = ldata.expr[labels == 1]
                for lo in range(0, len(lis), cfg.lanes):
                    idx = lis[lo:lo + cfg.lanes]
                    scores = biomarker_scores_lanes(
                        jnp.stack([lane_emb[li] for li in idx]),
                        expr_good, expr_poor,
                        jnp.stack([lg_dev[li] for li in idx]),
                        score_mix=cfg.score_mix)
                    sh = np.asarray(scores)   # writer-boundary transfer
                    for b, li in enumerate(idx):
                        scores_host[li] = sh[b]

        console(">>> [batch] 7. Save results (per lane)")
        fault_point("save")
        results: List[PipelineResult] = []
        out_dir = os.path.dirname(cfg.result_name)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with timer.stage("save"):
            for li, v in enumerate(variants):
                lgroup_host[li] = np.asarray(lg_dev[li])
                biomarkers, _ = top_biomarkers(
                    scores_host[li], lgroup_host[li], data.gene,
                    cfg.numBiomarker)
                name = f"{cfg.result_name}.{v.name}"
                emb_host = np.asarray(lane_emb[li])
                outputs = [
                    write_biomarkers(name, biomarkers),
                    write_lgroups(name, lgroup_host[li], data.gene),
                    write_vectors(name, emb_host, data.gene),
                ]
                r = lane_results[li]
                ldata = lane_data[v.expr_key()]
                results.append(PipelineResult(
                    genes=data.gene, embeddings=emb_host,
                    lgroup_idx=lgroup_host[li], biomarkers=biomarkers,
                    output_files=outputs,
                    n_samples=int(ldata.expr.shape[0]), n_genes=n_genes,
                    n_edges=n_edges, n_paths=int(payloads[li][0].shape[0]),
                    n_path_genes=len(payloads[li][2]),
                    train_history=r.history, acc_val=r.acc_val,
                    walker_backend=walker_backend,
                    sampler_threads=sampler_threads,
                    biomarker_scores=scores_host[li],
                    km_centers=km_centers[li]))
                lane_metrics[li].emit("done", outputs=outputs,
                                      stop_epoch=r.stop_epoch)
                for path in outputs:
                    console(f"    {path}")

        wall = time.time() - t_start
        rph = n_lanes / wall * 3600.0
        console(f"    [batch] {n_lanes} run(s) in {wall:.2f}s = "
                f"{rph:.1f} runs/hour  "
                f"(walks: {walk_stats['walked']} sampled, "
                f"{walk_stats['lane_shared']} lane-shared, "
                f"{walk_stats['disk_hits']} cache hits; "
                f"buckets: {[b['lanes'] for b in bucket_report]})")
        metrics.emit(
            "done", n_lanes=n_lanes, wall_seconds=round(wall, 3),
            runs_per_hour=round(rph, 2),
            stop_epochs={variants[li].tag(): lane_results[li].stop_epoch
                         for li in range(n_lanes)},
            walk_stats=walk_stats, buckets=bucket_report,
            stage_seconds=timer.as_dict())
        engine.batches_executed += 1
        engine.lanes_executed += n_lanes
        return BatchResult(
            lanes=results, variants=variants, wall_seconds=wall,
            runs_per_hour=rph, walk_stats=walk_stats,
            buckets=bucket_report, stage_seconds=timer.as_dict())
    finally:
        # The engine (and its pool) outlives this batch; forget only this
        # batch's tasks — waiting out any still in flight so the engine
        # returns to service with a quiet pool even on the failure path.
        overlap.prune(pfx)
        if own_metrics is not None:
            own_metrics.close()


def _make_walk_task(cfg, s, d, w, n_genes, *, seed, backend, tier, ckey,
                    group):
    """One distinct walk product: tier lookup (in-process memo, then the
    sha256-verified disk tier), else sample through the lane-shared
    backend and store. Runs on the overlap pool; the native sampler fans
    out into its own range pool exactly as in the solo pipeline."""

    def task():
        cached = tier.load(ckey)
        if cached is not None:
            return cached
        if backend == "native":
            from g2vec_tpu.ops.host_walker import generate_path_set_native

            ps = generate_path_set_native(
                s, d, w, n_genes, len_path=cfg.lenPath,
                reps=cfg.numRepetition, seed=seed,
                n_threads=cfg.sampler_threads)
        else:
            # Bit-exact device sampler (ops/device_walker.py): the same
            # splitmix64 rows the native branch emits, so the shared
            # NATIVE_FAMILY cache key is honest for both branches.
            from g2vec_tpu.ops.device_walker import generate_path_set_device

            ps = generate_path_set_device(
                s, d, w, n_genes, len_path=cfg.lenPath,
                reps=cfg.numRepetition, seed=seed)
        tier.store(ckey, ps, n_genes, meta={"group": group})
        return ps

    return task
