"""Benchmark: training + walker throughput at the bundled-example scale.

Prints JSON metric lines (one object per line, ``{"metric", "value",
"unit", "vs_baseline", ...}``), in this order:

1. ``cbow_train_paths_per_sec_per_chip`` — full-batch training of the
   two-matmul CBOW classifier on a 45,402 x 7,523 multi-hot path matrix,
   hidden=128. Each epoch is one fwd+bwd+Adam step over the whole 80% train
   split plus the val accuracy forward; the train accuracy rides the next
   epoch's grad forward (the eval-train fold, trainer.py — the reference
   instead re-runs a full train eval per epoch, ref: G2Vec.py:264-267;
   reported accuracies are identical). Baseline: the reference
   transcript's ~2.2 s/epoch steady state (README.md:36-40,
   BASELINE.md) with 36,321 train paths -> ~16.5k paths/s.
2. ``walker_walks_per_sec`` — stage 3, the reference's self-declared "most
   time consuming step" (ref: G2Vec.py:58): weighted no-revisit random
   walks (lenPath=80, reps=10) from every gene of the REAL bundled network
   (``/root/reference/ex_NETWORK.txt``: 9,904 genes, ~216k edges after the
   transcript's |PCC|-survival fraction — NOTE this is the full network's
   gene set, not the 7,523-gene per-group restriction of stage 3; synthetic
   scale-matched fallback when the mount is absent), sparse neighbor-table
   walker on device. Baseline: a bounded, degree-stratified in-process run
   of the reference's own per-node Python/NumPy walk loop (deepcopy +
   np.random.choice per step, ref: G2Vec.py:328-346) on this host,
   extrapolated to walks/s — the reference publishes no walker timing, so
   its own algorithm on the bench machine is the fairest anchor.
2b. ``walker_native_walks_per_sec`` — the same workload through the
   threaded C++ CSR sampler (ops/host_walker.py): the single-host
   no-accelerator path, and a walker number the round still gets if the
   TPU walker stage fails.
3. ``packed_matmul_vs_xla_dense`` — driver-verified kernel claim
   (packed_matmul.py docstring): the fused bit-packed Pallas matmul vs the
   XLA dense bf16 dot at the trainer's exact fwd shape; value = speedup.
4. ``cbow_epoch_breakdown`` — one epoch's cost split into its pieces
   (grad+Adam step, the two eval forwards) measured as standalone jitted
   programs at the trainer's shapes; shows where the non-roofline time
   goes (VERDICT r2 weak #2).
5. ``cbow_train_xla_dense_sec_per_epoch`` — the SAME trainer run with
   use_pallas=False: the epoch-structure-level XLA-dense control.
6. ``config2_*`` — BASELINE config #2 (hidden=512, lenPath=160): trainer
   sec/epoch and walker walks/s at the stressed shapes.

Stages 3-6 are budget-guarded: each is skipped (with a note line) if the
remaining child budget cannot cover its estimated compile+run cost, so the
two headline metrics always land within the driver's kill window.

Robustness (round-1 postmortem, VERDICT.md): the TPU tunnel can be down or
wedge indefinitely, and a raw crash/hang costs the round its only perf
artifact. So this script is a thin orchestrator that never imports jax
itself: it first PROBES the backend in a subprocess with a hard timeout
(retrying a flaky tunnel), then runs the measurement in a second bounded
subprocess. Every failure path prints a JSON-parseable error line and exits
nonzero within seconds of the deadline.

Chip-free rounds still record truth (round-3 postmortem: BENCH_r03 was
rc=2/value:null — the round recorded nothing): when the probe exhausts its
attempts, OR finds a healthy non-TPU backend with no explicit
G2VEC_BENCH_PLATFORM override (tunnel gone, jax fine — a full-scale CPU
train would burn the budget for nothing), a ``--_hostonly`` child that
never imports jax measures the native C++ sampler against the reference's
own walk loop and emits a real ``walker_native_walks_per_sec`` line
(printed last — the driver parses the last line), after an explicit
chip_free_fallback error line for the unmeasurable train headline. Exit
code 3 marks that mode (0 = chip bench, 2 = nothing measurable).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Reference transcript numbers (README.md:26-41, see BASELINE.md). The env
# overrides exist for smoke-testing the bench plumbing at toy scale (CI /
# CPU); driver runs use the defaults.
N_PATHS = int(os.environ.get("G2VEC_BENCH_N_PATHS", "45402"))
N_GENES = int(os.environ.get("G2VEC_BENCH_N_GENES", "7523"))
HIDDEN = int(os.environ.get("G2VEC_BENCH_HIDDEN", "128"))
VAL_FRACTION = 0.2
BASELINE_EPOCH_SECONDS = 2.2
BASELINE_PATHS_PER_SEC = int(N_PATHS * (1 - VAL_FRACTION)) / BASELINE_EPOCH_SECONDS

# Walker workload: every gene of the real network, reference CLI defaults.
LEN_PATH = int(os.environ.get("G2VEC_BENCH_LEN_PATH", "80"))
WALKER_REPS = int(os.environ.get("G2VEC_BENCH_WALKER_REPS", "10"))
REFERENCE_NETWORK = "/root/reference/ex_NETWORK.txt"

# The trainer runs epochs in device-resident chunks of DEFAULT_CHUNK (=128)
# epochs per dispatch; per-epoch times inside a chunk are uniform. The first
# measured chunk absorbs the host->device transfer of the (bit-packed) path
# matrix, so steady state is read from the chunks after it. A separate
# warmup call compiles the chunk program (the jit cache is shared across
# train_cbow calls).
# Warmup 0 = exactly one DEFAULT_CHUNK of epochs: the chunk program's shape
# depends on min(DEFAULT_CHUNK, max_epochs), so a shorter warmup would
# compile a different program than the measured run uses.
WARMUP_EPOCHS = int(os.environ.get("G2VEC_BENCH_WARMUP_EPOCHS", "0"))
# Seconds granted to the reference-loop baseline sample (toy-scale
# subprocess tests shrink it; real rounds keep the full stable sample).
BASELINE_BUDGET = float(os.environ.get("G2VEC_BENCH_BASELINE_BUDGET", "12"))
# The metrics only a live chip can produce: a chip-free round emits each
# as an explicit null (tests pin the full surface against this tuple).
GATED_CHIP_METRICS = (("walker_walks_per_sec", "walks/s"),
                      ("walker_restricted_walks_per_sec", "walks/s"),
                      ("tpu_acceptance_acc_val", "ACC[val]"),
                      ("packed_matmul_vs_xla_dense", "x"),
                      ("cbow_epoch_breakdown", "ms"),
                      ("cbow_train_xla_dense_sec_per_epoch", "s"),
                      ("config2_train_paths_per_sec_per_chip", "paths/s"),
                      ("config2_walker_walks_per_sec", "walks/s"))
MEASURE_EPOCHS = int(os.environ.get("G2VEC_BENCH_MEASURE_EPOCHS", "192"))

PROBE_TIMEOUT = int(os.environ.get("G2VEC_BENCH_PROBE_TIMEOUT", "75"))
PROBE_ATTEMPTS = 3
MEASURE_TIMEOUT = int(os.environ.get("G2VEC_BENCH_TIMEOUT", "430"))
# If the measure child has produced NO metric line by this point, it is
# wedged (the headline train stage needs ~60-90s including its compile) —
# kill it and retry once while budget remains. Round-3 postmortem: the
# tunnel wedged between the probe and the measure child, and the child
# burned the entire 430s window producing nothing; a 210s cutoff leaves a
# second attempt with real odds.
FIRST_METRIC_TIMEOUT = int(os.environ.get("G2VEC_BENCH_FIRST_METRIC", "210"))
# Hard wall for the whole script: stay under the driver's ~560s kill so a
# wedge ALWAYS yields a JSON line, never an rc=124 with empty output.
TOTAL_BUDGET = int(os.environ.get("G2VEC_BENCH_TOTAL_BUDGET", "520"))
# Soft deadline inside the measurement child for the optional stages.
CHILD_BUDGET = int(os.environ.get("G2VEC_BENCH_CHILD_BUDGET", "400"))

# Batched-vs-sequential runs/hour A/B (batch/engine.py): variants in the
# seed-sweep manifest, min-of-N reps, trainer epochs, and a synthetic
# gene-scale multiplier. Defaults are CPU-safe tiny shapes; the
# subprocess tests shrink further via these envs.
BATCH_AB_VARIANTS = int(os.environ.get("G2VEC_BENCH_BATCH_VARIANTS", "8"))
BATCH_AB_REPS = int(os.environ.get("G2VEC_BENCH_BATCH_REPS", "3"))
BATCH_AB_EPOCHS = int(os.environ.get("G2VEC_BENCH_BATCH_EPOCHS", "30"))
BATCH_AB_SCALE = int(os.environ.get("G2VEC_BENCH_BATCH_SCALE", "1"))
BATCH_AB_ARTIFACT = "BENCH_BATCH_AB.json"

# Scenario-engine A/B (stats/): a bootstrap stability study as ONE
# lane-amortized --scenario process vs the pre-engine workflow (a fresh
# process per replicate, each passing its derived seed by hand).
# Defaults are CPU-safe tiny shapes; tests shrink further via these envs.
SCN_AB_REPLICATES = int(os.environ.get("G2VEC_BENCH_SCN_REPLICATES", "6"))
SCN_AB_REPS = int(os.environ.get("G2VEC_BENCH_SCN_REPS", "2"))
SCN_AB_EPOCHS = int(os.environ.get("G2VEC_BENCH_SCN_EPOCHS", "30"))
SCN_AB_SCALE = int(os.environ.get("G2VEC_BENCH_SCN_SCALE", "1"))
SCN_AB_ARTIFACT = "BENCH_SCENARIO_AB.json"

# Resident-service A/B (serve/daemon.py): Poisson job arrivals against the
# warm daemon vs a fresh process per job at the SAME arrival schedule.
# Defaults are CPU-safe tiny shapes; the subprocess tests shrink further.
SERVE_AB_JOBS = int(os.environ.get("G2VEC_BENCH_SERVE_JOBS", "8"))
SERVE_AB_REPS = int(os.environ.get("G2VEC_BENCH_SERVE_REPS", "3"))
SERVE_AB_EPOCHS = int(os.environ.get("G2VEC_BENCH_SERVE_EPOCHS", "30"))
SERVE_AB_MEAN_ARRIVAL_S = float(
    os.environ.get("G2VEC_BENCH_SERVE_ARRIVAL", "1.0"))
SERVE_AB_SCALE = int(os.environ.get("G2VEC_BENCH_SERVE_SCALE", "1"))
SERVE_AB_ARTIFACT = "BENCH_SERVE_AB.json"

# Streaming-vs-full-batch trainer A/B (train/stream.py): min-of-N reps at
# the bundled-scale synthetic, plus a scale-free big-graph axis
# (data/synth.py) where the walk-path volume grows while the streaming
# arm's host memory must NOT. Defaults are 1-core-safe; env-shrinkable
# like every other net here.
STREAM_AB_REPS = int(os.environ.get("G2VEC_BENCH_STREAM_REPS", "3"))
STREAM_AB_EPOCHS = int(os.environ.get("G2VEC_BENCH_STREAM_EPOCHS", "30"))
STREAM_AB_GENES = int(os.environ.get("G2VEC_BENCH_STREAM_GENES", "6000"))
STREAM_AB_BIG_EPOCHS = int(os.environ.get("G2VEC_BENCH_STREAM_BIG_EPOCHS",
                                          "4"))
STREAM_AB_WALK_REPS = tuple(int(x) for x in os.environ.get(
    "G2VEC_BENCH_STREAM_WALK_REPS", "4,12").split(","))
STREAM_AB_ARTIFACT = "BENCH_STREAM_AB.json"

# On-device walk sampling A/B (ops/device_walker.py, PR 20): paths/s for
# the bit-exact splitmix64 device CSR sampler vs the host C++ pool at
# the same shard plan (byte identity re-checked shard-by-shard IN-RUN,
# the A/B aborts on any mismatch), plus the fused --device-feed
# streaming arm vs the host ring (time-to-first-update, end-to-end
# wall, h2d_bytes_saved, zero-ring-puts). The CPU numbers bound
# dispatch/kernel overhead only — the H2D-elision win is chip-shaped,
# so the chip sweep lines are emitted as explicit nulls off-chip
# (watcher-gated), never faked from CPU timings. Env-shrinkable.
DEVICE_WALK_GENES = int(os.environ.get("G2VEC_BENCH_DEVICE_GENES", "4000"))
DEVICE_WALK_EDGES = int(os.environ.get("G2VEC_BENCH_DEVICE_EDGES", "24000"))
DEVICE_WALK_LEN = int(os.environ.get("G2VEC_BENCH_DEVICE_LEN", "40"))
DEVICE_WALK_WREPS = int(os.environ.get("G2VEC_BENCH_DEVICE_WREPS", "2"))
DEVICE_WALK_TIMING_REPS = int(os.environ.get("G2VEC_BENCH_DEVICE_REPS", "3"))
DEVICE_WALK_SHARDS = int(os.environ.get("G2VEC_BENCH_DEVICE_SHARDS", "6"))
DEVICE_FEED_GENES = int(os.environ.get("G2VEC_BENCH_DEVICE_FEED_GENES",
                                       "1200"))
DEVICE_FEED_EPOCHS = int(os.environ.get("G2VEC_BENCH_DEVICE_FEED_EPOCHS",
                                        "2"))
DEVICE_WALK_ARTIFACT = "BENCH_DEVICE_WALK.json"

# Chaos soak (tools/chaos_soak.py): a seeded fault storm against the
# serve daemon — SIGKILLs, SIGTERM drains, armed fault plans at the
# durable seams, client cancels and tight deadlines — whose acceptance
# is exactly-once accounting: every acknowledged job reaches exactly one
# well-defined terminal state, zero lost/duplicated, sampled completed
# outputs byte-identical to solo uninterrupted runs. Env-shrinkable.
CHAOS_JOBS = int(os.environ.get("G2VEC_BENCH_CHAOS_JOBS", "50"))
CHAOS_SEED = int(os.environ.get("G2VEC_BENCH_CHAOS_SEED", "0"))
CHAOS_BUDGET = float(os.environ.get("G2VEC_BENCH_CHAOS_BUDGET", "900"))
CHAOS_ARTIFACT = "BENCH_CHAOS_SOAK.json"
ROUTER_CHAOS_JOBS = int(os.environ.get("G2VEC_BENCH_ROUTER_JOBS", "50"))
ROUTER_CHAOS_REPLICAS = int(os.environ.get("G2VEC_BENCH_ROUTER_REPLICAS",
                                           "3"))
ROUTER_CHAOS_SEED = int(os.environ.get("G2VEC_BENCH_ROUTER_SEED", "0"))
ROUTER_CHAOS_BUDGET = float(os.environ.get("G2VEC_BENCH_ROUTER_BUDGET",
                                           "1200"))
ROUTER_CHAOS_ARTIFACT = "BENCH_ROUTER_CHAOS.json"

# Elastic autoscaling A/B (serve/router.py scaling controller +
# serve/daemon.py tenant SLOs): one seeded diurnal+burst schedule of
# tenant-tagged jobs (gold/silver/bulk, distinct deadlines and compile
# shapes), one replica SIGKILLed mid-spike, run twice — a static
# 1-replica fleet vs the elastic fleet (ceiling 2, one pre-warmed
# spare, shed + quotas). Acceptance: static reproduces the
# deadline-death failure mode (>= 4 of 50), elastic holds it to <= 1
# with per-tenant SLO attainment at least as good, and BOTH arms keep
# exactly-once accounting (0 lost / 0 duplicated) across every scale
# and kill event.
AUTOSCALE_JOBS = int(os.environ.get("G2VEC_BENCH_AUTOSCALE_JOBS", "50"))
AUTOSCALE_SEED = int(os.environ.get("G2VEC_BENCH_AUTOSCALE_SEED", "11"))
AUTOSCALE_BUDGET = float(os.environ.get("G2VEC_BENCH_AUTOSCALE_BUDGET",
                                        "420"))
AUTOSCALE_QUOTAS = os.environ.get(
    "G2VEC_BENCH_AUTOSCALE_QUOTAS",
    "gold:6:12:3;silver:3:6:2;bulk:0.8:2:1")
AUTOSCALE_ARTIFACT = "BENCH_AUTOSCALE.json"

# Partition-tolerant control plane (serve/leader.py + router fencing +
# daemon self-quarantine + degraded-mode clients): the relay-blackhole
# drill from tools/chaos_soak.py --partition. One replica is
# partitioned-while-alive (the router must fence + migrate, the replica
# must self-quarantine off the shared-disk marker and stay out of the
# ring after the heal), the active router is SIGSTOPped past its lease
# ttl (every mutating command the zombie then emits must die with the
# structured stale_epoch rejection), and a chain of router SIGKILLs is
# ridden out by standbys with degraded-mode client drills in each gap.
# Acceptance: fleet-wide exactly-once, zero post-fence output from the
# quarantined replica, all stale epochs rejected, every takeover
# completed. Env-shrinkable.
PARTITION_JOBS = int(os.environ.get("G2VEC_BENCH_PARTITION_JOBS", "18"))
PARTITION_SEED = int(os.environ.get("G2VEC_BENCH_PARTITION_SEED", "5"))
PARTITION_TAKEOVERS = int(os.environ.get(
    "G2VEC_BENCH_PARTITION_TAKEOVERS", "3"))
PARTITION_BUDGET = float(os.environ.get("G2VEC_BENCH_PARTITION_BUDGET",
                                        "900"))
PARTITION_ARTIFACT = "BENCH_PARTITION.json"

# Interactive query plane (serve/inventory.py + ops/knn.py): seeded
# Poisson query load against a replicated fleet, concurrent with
# training jobs, one replica SIGKILLed mid-run. Cold = first touch of a
# freshly published bundle (mmap + manifest sha); warm = everything
# after. Acceptance: warm p99 under QUERY_P99_MS for both neighbors and
# topk_biomarkers, zero query errors, and a kernel-vs-disk exactness
# spot check. Env-shrinkable.
QUERY_JOBS = int(os.environ.get("G2VEC_BENCH_QUERY_JOBS", "6"))
QUERY_BG_JOBS = int(os.environ.get("G2VEC_BENCH_QUERY_BG_JOBS", "3"))
QUERY_REPLICAS = int(os.environ.get("G2VEC_BENCH_QUERY_REPLICAS", "3"))
QUERY_SEED = int(os.environ.get("G2VEC_BENCH_QUERY_SEED", "0"))
QUERY_RATE = float(os.environ.get("G2VEC_BENCH_QUERY_RATE", "40"))
QUERY_DURATION = float(os.environ.get("G2VEC_BENCH_QUERY_DURATION", "25"))
QUERY_P99_MS = float(os.environ.get("G2VEC_BENCH_QUERY_P99_MS", "10"))
QUERY_ARTIFACT = "BENCH_QUERY.json"

# Approximate-NN query plane A/B (ops/ann.py + the serve read plane):
# (a) in-process QPS frontier, IVF-approx vs exact full-scan, over
# growing bundle sizes — acceptance is approx >= ANN_SPEEDUP_MIN x
# exact QPS at the LARGEST size with approx per-query p99 under
# ANN_P99_MS and recall@10 >= 0.95 at the default nprobe; (b) the
# recall@10 curve over nprobe (ending at nprobe=nlist, which must be
# bitwise-equal to exact); (c) a federated fquery storm against a live
# router fleet with one bundle-owning replica SIGKILLed mid-window —
# dead bundles keep answering from shared disk with replica_down
# attribution and zero errors. Env-shrinkable.
ANN_SIZES = os.environ.get("G2VEC_BENCH_ANN_SIZES",
                           "8192,32768,131072,262144")
ANN_HIDDEN = int(os.environ.get("G2VEC_BENCH_ANN_HIDDEN", "64"))
ANN_QUERIES = int(os.environ.get("G2VEC_BENCH_ANN_QUERIES", "400"))
ANN_RECALL_QUERIES = int(os.environ.get(
    "G2VEC_BENCH_ANN_RECALL_QUERIES", "64"))
ANN_NPROBES = os.environ.get("G2VEC_BENCH_ANN_NPROBES", "1,2,4,8,16,32")
ANN_SPEEDUP_MIN = float(os.environ.get("G2VEC_BENCH_ANN_SPEEDUP_MIN", "5"))
ANN_P99_MS = float(os.environ.get("G2VEC_BENCH_ANN_P99_MS", "10"))
ANN_FED_REPLICAS = int(os.environ.get("G2VEC_BENCH_ANN_FED_REPLICAS", "3"))
ANN_FED_BUNDLES = int(os.environ.get("G2VEC_BENCH_ANN_FED_BUNDLES", "6"))
ANN_FED_GENES = int(os.environ.get("G2VEC_BENCH_ANN_FED_GENES", "6000"))
ANN_FED_RATE = float(os.environ.get("G2VEC_BENCH_ANN_FED_RATE", "30"))
ANN_FED_DURATION = float(os.environ.get("G2VEC_BENCH_ANN_FED_DURATION",
                                        "15"))
ANN_FED_P99_MS = float(os.environ.get("G2VEC_BENCH_ANN_FED_P99_MS", "100"))
ANN_SEED = int(os.environ.get("G2VEC_BENCH_ANN_SEED", "0"))
ANN_ARTIFACT = "BENCH_ANN.json"

# Incremental update plane A/B (incremental.py + the serve update op):
# cold pipeline run -> published bundle -> bootstrap update (records
# per-range walk artifacts + fingerprints) -> (a) no-op re-update,
# which must walk ZERO rows and republish byte-identical array files;
# (b) a ~UPDATE_DELTA_FRAC edge delta, where the delta re-walk +
# warm-start fine-tune must land within UPDATE_WALL_FRAC x the wall of
# a cold retrain of the SAME updated inputs while holding the PR 7
# statistical band against it; (c) a torn-read probe — at least
# UPDATE_MIN_READS serve-path queries spanning UPDATE_FLIPS generation
# flips, every answer a complete pre-flip or post-flip result.
# The synthetic cohort is a scaled-up cousin of the band-validated
# tests/test_update.py spec: enough patients that BOTH training
# trajectories converge to the planted-module answer, and enough walk
# volume that the walls measure the delta plane rather than fixed
# per-run overheads. Env-shrinkable.
UPDATE_GOOD = int(os.environ.get("G2VEC_BENCH_UPDATE_GOOD", "48"))
UPDATE_POOR = int(os.environ.get("G2VEC_BENCH_UPDATE_POOR", "40"))
UPDATE_MODULE = int(os.environ.get("G2VEC_BENCH_UPDATE_MODULE", "16"))
UPDATE_SMOD = int(os.environ.get("G2VEC_BENCH_UPDATE_SMOD", "20"))
UPDATE_BG = int(os.environ.get("G2VEC_BENCH_UPDATE_BG", "24"))
UPDATE_BG_EDGES = int(os.environ.get("G2VEC_BENCH_UPDATE_BG_EDGES",
                                     "40"))
UPDATE_NBIO = int(os.environ.get("G2VEC_BENCH_UPDATE_NBIO", "16"))
UPDATE_LENPATH = int(os.environ.get("G2VEC_BENCH_UPDATE_LENPATH", "32"))
UPDATE_REPS = int(os.environ.get("G2VEC_BENCH_UPDATE_REPS", "48"))
UPDATE_EPOCH = int(os.environ.get("G2VEC_BENCH_UPDATE_EPOCH", "60"))
UPDATE_DELTA_FRAC = float(os.environ.get(
    "G2VEC_BENCH_UPDATE_DELTA_FRAC", "0.005"))
UPDATE_WALL_FRAC = float(os.environ.get(
    "G2VEC_BENCH_UPDATE_WALL_FRAC", "0.35"))
UPDATE_MIN_READS = int(os.environ.get("G2VEC_BENCH_UPDATE_MIN_READS",
                                      "100"))
UPDATE_FLIPS = int(os.environ.get("G2VEC_BENCH_UPDATE_FLIPS", "8"))
UPDATE_SEED = int(os.environ.get("G2VEC_BENCH_UPDATE_SEED", "7"))
UPDATE_ARTIFACT = "BENCH_UPDATE.json"

# Million-node shard-scale sweep (parallel/shard.py + train/shard.py):
# "genes:ranks" cells, run as real multi-process fleets of
# tests/shard_worker.py over the KV transport. The diagonal (constant
# genes/ranks) is the claim: per-rank peak RSS stays ~flat while the
# graph grows with the rank count. Env-shrinkable for smoke tests.
SHARD_SCALE_GRID = os.environ.get(
    "G2VEC_BENCH_SHARD_GRID",
    "262144:1,262144:2,524288:2,524288:4,1048576:4,1048576:1")
SHARD_SCALE_HIDDEN = int(os.environ.get("G2VEC_BENCH_SHARD_HIDDEN", "128"))
SHARD_SCALE_STARTS = int(os.environ.get("G2VEC_BENCH_SHARD_STARTS", "2048"))
SHARD_SCALE_CELL_TIMEOUT = int(os.environ.get(
    "G2VEC_BENCH_SHARD_CELL_TIMEOUT", "2400"))
SHARD_SCALE_RSS_FLAT = 1.3     # diagonal max/min per-rank peak RSS bound
SHARD_SCALE_ARTIFACT = "BENCH_SHARD_SCALE.json"

# Edge-partitioned CSR A/B (--edge-partition): full-CSR graph-sharded
# fleet vs owner-range CSRs under BOTH boundary strategies (handoff,
# halo) at the same scale — per-rank graph bytes, peak RSS, and path
# throughput. Env-shrinkable for smoke tests.
EDGE_AB_GENES = int(os.environ.get("G2VEC_BENCH_EDGE_GENES", "1048576"))
EDGE_AB_RANKS = int(os.environ.get("G2VEC_BENCH_EDGE_RANKS", "4"))
EDGE_AB_HIDDEN = int(os.environ.get("G2VEC_BENCH_EDGE_HIDDEN", "128"))
EDGE_AB_STARTS = int(os.environ.get("G2VEC_BENCH_EDGE_STARTS", "2048"))
EDGE_AB_TIMEOUT = int(os.environ.get("G2VEC_BENCH_EDGE_TIMEOUT", "3600"))
EDGE_AB_ARTIFACT = "BENCH_EDGE_PARTITION.json"

# Peak bf16 matmul throughput per chip, for the MFU estimate.
_PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
# HBM bandwidth per chip (bytes/s): the roofline's other axis. This
# workload's matmuls are skinny (h=128 lanes), so the breakdown stage
# reports each piece's implied bandwidth against this peak to show where
# sec/epoch actually caps (VERDICT r4 task 2).
_PEAK_HBM = {"v4": 1228e9, "v5e": 819e9, "v5p": 2765e9, "v6e": 1638e9}


def _fail(stage: str, detail: str, code: int = 2) -> "NoReturn":  # noqa: F821
    print(json.dumps({
        "metric": "cbow_train_paths_per_sec_per_chip", "value": None,
        "unit": "paths/s", "vs_baseline": None,
        "error": f"{stage}: {detail}"[:500],
    }))
    sys.exit(code)


# --------------------------------------------------------------------------
# Parent orchestrator (no jax import in this process, ever).
# --------------------------------------------------------------------------

def main() -> None:
    deadline = time.time() + TOTAL_BUDGET
    last_err = "?"
    probe_platform = ""
    for attempt in range(PROBE_ATTEMPTS):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--_probe"],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {PROBE_TIMEOUT}s"
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            info = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"# backend probe ok: {info}", file=sys.stderr)
            probe_platform = info.get("platform", "")
            break
        last_err = (proc.stderr or proc.stdout or "")[-300:]
        time.sleep(5)
    else:
        _hostonly_fallback(f"no usable jax backend after {PROBE_ATTEMPTS} "
                           f"attempts: {last_err}", deadline)

    if probe_platform != "tpu" and not os.environ.get("G2VEC_BENCH_PLATFORM"):
        # A healthy NON-chip backend (ambient CPU: tunnel gone but jax
        # fine) would burn the whole budget on a full-scale CPU train and
        # record nothing. An explicit G2VEC_BENCH_PLATFORM override is
        # operator intent (smoke tests at toy scale) and proceeds; an
        # ambient non-TPU backend is a chip-free round — record the
        # chip-free truths instead.
        _hostonly_fallback(
            f"backend probe found '{probe_platform}', not tpu "
            f"(no chip this round)", deadline)

    out = err = ""
    fail = None
    for attempt in range(2):
        budget = max(60, min(MEASURE_TIMEOUT, int(deadline - time.time())))
        # The child's soft deadline must sit INSIDE the parent's kill
        # window, or a budget-guarded stage can start right before the
        # hard kill.
        child_env = dict(os.environ,
                         G2VEC_BENCH_CHILD_BUDGET=str(
                             min(CHILD_BUDGET, max(30, budget - 20))))
        # The pre-metric wedge cutoff calibrates to the TPU path (train's
        # first metric lands within ~90s there). On other backends the
        # same stage can legitimately run past it — a CPU headline train
        # takes minutes — so only the budget kill applies.
        cutoff = FIRST_METRIC_TIMEOUT if probe_platform == "tpu" else budget
        out, err, fail = _run_measure_child(budget, child_env, cutoff)
        sys.stderr.write(err)
        # Retry only the produced-nothing wedge (transient tunnel death
        # between probe and measure): a child that got ANY metric out is
        # relayed as-is — its failures are stage-level, not backend-level.
        if attempt == 1 or not (fail and not _has_real_metric(out)
                                and deadline - time.time() > 90):
            break
        print(f"# measure attempt {attempt + 1} produced no metric "
              f"({fail}); retrying", file=sys.stderr, flush=True)
    # Relay whatever metric lines the child DID produce before dying — the
    # headline train line prints the moment it exists, so a later-stage
    # wedge must not cost the round the training number.
    sys.stdout.write(out)
    if fail is not None:
        if out and not out.endswith("\n"):
            print()     # a killed child may leave a partial line behind
        stage_error = {"metric": "bench_stage_error", "value": None,
                       "unit": "", "vs_baseline": None,
                       "error": f"measure: {fail}: {err[-300:]}"[:500]}
        if _has_real_metric(out):
            # Partial success: headline survived; record the stage failure
            # under a non-colliding metric name.
            print(json.dumps(stage_error))
        else:
            # The child died before ANY metric (tunnel wedged mid-train).
            # In-round chip evidence that already landed must still reach
            # the round's record: relay it (headline last) and exit 3 —
            # the partial-success code — instead of the rc=2 nothing.
            landed = _landed_window_lines(
                os.environ.get("G2VEC_BENCH_WINDOW_DIR") or None)
            if landed:
                print(json.dumps(stage_error))
                reason = "this run's chip measurement died pre-metric"
                headline = landed.pop("cbow_train_paths_per_sec_per_chip",
                                      None)
                for metric in landed:
                    print(json.dumps(_relay_line(*landed[metric],
                                                 reason=reason)))
                if headline:
                    print(json.dumps(_relay_line(*headline, reason=reason)))
                else:
                    # The headline metric must always close the record —
                    # as an explicit honest null when no window landed it
                    # (same contract as _fail/_hostonly) — so the
                    # driver's parsed last line stays semantic.
                    print(json.dumps(
                        {"metric": "cbow_train_paths_per_sec_per_chip",
                         "value": None, "unit": "paths/s",
                         "vs_baseline": None,
                         "error": f"measure: {fail}"[:500]}))
                sys.exit(3)
            _fail("measure", f"{fail}: {err[-300:]}")


def _hostonly_fallback(probe_err: str, deadline: float) -> "NoReturn":  # noqa: F821
    """Chip-free round — the probe exhausted its attempts OR found a
    healthy non-TPU backend: emit the chip-free truths instead of only an
    error object (round-3 postmortem — BENCH_r03 was rc=2/value:null and
    the round recorded NOTHING). Runs ``--_hostonly`` in a child that
    never imports jax: the native C++ sampler and the reference-loop
    baseline are host work, so their numbers are true with no backend.
    ``probe_err`` states which of the two states was detected, verbatim,
    in the headline error line and the stderr note. The real metric
    prints LAST (the driver's parsed field reads the last line). Exits 3
    — distinct from rc=0 (chip bench) and rc=2 (nothing) — when at least
    one real metric landed.
    """
    print(f"# chip-free round ({probe_err}); falling back to "
          f"host-only metrics", file=sys.stderr, flush=True)
    # The headline train metric is unmeasurable without a backend: say so
    # first, in-band, so no reader mistakes the fallback for a chip round.
    print(json.dumps({
        "metric": "cbow_train_paths_per_sec_per_chip", "value": None,
        "unit": "paths/s", "vs_baseline": None,
        "error": f"backend-probe: {probe_err}"[:500],
        "chip_free_fallback": True,
    }), flush=True)
    remaining = int(deadline - time.time() - 10)
    if remaining <= 0:
        # Probe retries already ate the driver's budget: a >=30s child here
        # would overrun the deadline and risk an external kill that loses
        # the partial-line cleanup below. Bail with the error line only.
        print(f"# no budget left for the host-only child "
              f"({remaining}s past safe margin)", file=sys.stderr)
        sys.exit(2)
    budget = min(180, remaining)   # floor is the remaining time, never past it
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_hostonly"],
            capture_output=True, text=True, timeout=budget)
        sys.stderr.write(proc.stderr)
        sys.stdout.write(proc.stdout)
        ok = _has_real_metric(proc.stdout)
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries raw bytes even under text=True.
        out = (e.stdout or b"").decode(errors="replace")
        print(f"# host-only child exceeded {budget}s", file=sys.stderr)
        sys.stderr.write((e.stderr or b"").decode(errors="replace"))
        sys.stdout.write(out)
        if out and not out.endswith("\n"):
            print()   # a killed child may leave a partial line behind
        ok = _has_real_metric(out)
    sys.exit(3 if ok else 2)


def _cli_sampler_threads() -> int:
    """--sampler-threads N from this invocation's argv (or the
    G2VEC_BENCH_SAMPLER_THREADS env); 0 = auto (all cores)."""
    env = os.environ.get("G2VEC_BENCH_SAMPLER_THREADS")
    val = env if env else None
    if "--sampler-threads" in sys.argv:
        idx = sys.argv.index("--sampler-threads")
        if idx + 1 >= len(sys.argv):
            _fail("args", "--sampler-threads needs a value")
        val = sys.argv[idx + 1]
    if val is None:
        return 0
    try:
        n = int(val)
    except ValueError:
        _fail("args", f"--sampler-threads must be an int, got {val!r}")
    if n < 0:
        _fail("args", f"--sampler-threads must be >= 0, got {n}")
    return n


def _native_walker_line(src, dst, w, n_genes: int, baseline: float,
                        note, extra: dict, metric: str =
                        "walker_native_walks_per_sec",
                        len_path: "int | None" = None,
                        n_threads: int = 0) -> dict:
    """Time the native C++ sampler on the bench walk workload and build the
    ``walker_native_walks_per_sec`` metric line. ONE implementation for the
    chip-round stage 2b and the dead-tunnel host-only child, so the two
    rounds' numbers stay comparable field-for-field. Never imports jax.
    ``len_path`` overrides the bench default (config #2 runs 160)."""
    from g2vec_tpu.native.walker_bindings import load as load_native
    from g2vec_tpu.ops.host_walker import (generate_path_set_native,
                                           resolve_sampler_threads)

    lp = LEN_PATH if len_path is None else len_path
    threads = resolve_sampler_threads(n_threads)
    load_native()              # one-time g++ compile outside the timed region
    t0 = time.time()
    npaths = generate_path_set_native(src, dst, w, n_genes,
                                      len_path=lp, reps=WALKER_REPS,
                                      seed=0, n_threads=threads)
    el = time.time() - t0
    total_n = n_genes * WALKER_REPS
    note(f"native walker (len_path={lp}, threads={threads}): {total_n} "
         f"walks in {el:.2f}s -> {total_n / el:.0f} walks/s; "
         f"{len(npaths)} unique paths")
    return {"metric": metric,
            "value": round(total_n / el, 1), "unit": "walks/s",
            "vs_baseline": round(total_n / el / baseline, 2),
            "unique_paths": len(npaths), "n_genes": n_genes,
            "len_path": lp, "reps": WALKER_REPS,
            "sampler_threads": threads, **extra}


def _mt_speedup_line(src, dst, w, n_genes: int, note) -> dict:
    """``walker_native_mt_speedup``: the SAME walk workload once on one
    thread and once on the resolved --sampler-threads pool, with the
    bit-identity of the two outputs checked on the spot — the multicore
    win is measured (and its determinism contract verified), never
    asserted. Raw walk_packed_rows (pre-dedup) so the rows admit an exact
    array compare. Never imports jax."""
    import numpy as np

    from g2vec_tpu.ops.host_walker import (resolve_sampler_threads,
                                           walk_packed_rows)

    threads = resolve_sampler_threads(_cli_sampler_threads())
    kwargs = dict(len_path=LEN_PATH, reps=WALKER_REPS, seed=0)
    t0 = time.time()
    rows1 = walk_packed_rows(src, dst, w, n_genes, n_threads=1, **kwargs)
    el1 = time.time() - t0
    t0 = time.time()
    rows_n = walk_packed_rows(src, dst, w, n_genes, n_threads=threads,
                              **kwargs)
    el_n = time.time() - t0
    bit_identical = bool(np.array_equal(rows1, rows_n))
    total_n = n_genes * WALKER_REPS
    note(f"native sampler scaling: 1 thread {total_n / el1:.0f} walks/s vs "
         f"{threads} thread(s) {total_n / el_n:.0f} walks/s "
         f"({el1 / el_n:.2f}x); bit_identical={bit_identical}")
    line = {"metric": "walker_native_mt_speedup",
            "value": round(el1 / el_n, 2), "unit": "x",
            "vs_baseline": None, "sampler_threads": threads,
            "host_cores": os.cpu_count() or 1,
            "single_thread_walks_per_sec": round(total_n / el1, 1),
            "threaded_walks_per_sec": round(total_n / el_n, 1),
            "bit_identical": bit_identical, "n_genes": n_genes,
            "len_path": LEN_PATH, "reps": WALKER_REPS}
    if not bit_identical:
        # A determinism break outranks any speedup claim.
        line["error"] = (f"{threads}-thread rows differ from the 1-thread "
                         f"ordering — per-walker stream keying is broken")
        line["value"] = None
    elif threads == 1:
        line["note"] = ("resolved to 1 thread (single-core host or pinned "
                        "--sampler-threads 1): no parallel speedup to "
                        "measure, bit-identity still verified")
    return line


def _current_code_key(repo_dir: str) -> "str | None":
    """Tree hash of HEAD:g2vec_tpu (the acceptance artifacts' freshness
    key, tools/tpu_acceptance._code_key without the dirty suffix)."""
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD:g2vec_tpu"],
                             cwd=repo_dir, capture_output=True, text=True,
                             timeout=10)
        return out.stdout.strip() or None
    except Exception:  # noqa: BLE001 — freshness ranking is best-effort
        return None


def _epochs_to_088_line(artifact_dir: "str | None" = None) -> dict:
    """BASELINE.json's second target metric — epochs to val-ACC >= 0.88 —
    read from the best acceptance artifact that recorded a training
    history (tools/tpu_acceptance.py writes ``epochs_to_acc_088``).
    Ranking: artifacts whose code_key matches the CURRENT HEAD:g2vec_tpu
    tree outrank stale ones (a weeks-old chip artifact must not shadow a
    freshly regenerated CPU twin); within a freshness class, TPU
    outranks CPU. The reference transcript crosses at epoch 25 with
    0.8812 (/root/reference/README.md:35-41), so vs_baseline > 1 means
    we converge in FEWER epochs. No jax anywhere: safe for the host-only
    child."""
    ref_epochs = 25
    here = artifact_dir or os.path.dirname(os.path.abspath(__file__))
    current_key = _current_code_key(here)
    candidates = []
    for rank, name in enumerate(("TPU_ACCEPTANCE.json",
                                 "REAL_ACCEPTANCE.json")):
        path = os.path.join(here, name)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                art = json.load(f)
        except ValueError:
            continue
        if "epochs_to_acc_088" not in art:
            continue    # pre-r5 artifact without a history record
        fresh = bool(current_key) and art.get("code_key") == current_key
        candidates.append((0 if fresh else 1, rank, name, art, fresh))
    if not candidates:
        return {"metric": "epochs_to_acc_0.88", "value": None,
                "unit": "epochs", "vs_baseline": None,
                "error": "no acceptance artifact records a training history"}
    _, _, name, art, fresh = min(candidates)
    epochs = art["epochs_to_acc_088"]
    line = {"metric": "epochs_to_acc_0.88", "value": epochs,
            "unit": "epochs", "baseline_epochs": ref_epochs,
            "platform": art.get("platform"),
            "acc_val": round(art.get("acc_val", 0.0), 4),
            "n_epochs_run": art.get("n_epochs_run"),
            "source_artifact": name,
            "source_git_head": (art.get("git_head") or "")[:12],
            "code_fresh": fresh}
    if epochs is None:
        line["vs_baseline"] = None
        line["error"] = "run never reached ACC[val] >= 0.88"
    else:
        line["vs_baseline"] = round(ref_epochs / max(epochs, 1), 2)
    return line


def _landed_window_lines(window_dir: "str | None" = None) -> dict:
    """metric -> (line, artifact_basename) salvaged from THIS round's
    committed chip-window artifacts (the watcher battery's
    BENCH_LOCAL_{round}*.json). A dead tunnel at driver bench time must
    not erase chip numbers that DID land at HEAD earlier in the round —
    the fallback relays them with provenance instead of printing nulls.
    Round-scoped glob (G2VEC_BENCH_WINDOW_ROUND, or the watcher's
    WATCHER_ROUND — itself defaulted from the single-sourced tools/ROUND
    file) so a later round can never relay a stale round's lines as
    current. With NEITHER env var set the relay is SKIPPED with a warning
    (ADVICE r5 #2): guessing a round here is exactly how stale numbers
    get re-stamped as current. Later files win per metric."""
    import glob as _glob

    here = window_dir if window_dir is not None \
        else os.path.dirname(os.path.abspath(__file__))
    rnd = os.environ.get("G2VEC_BENCH_WINDOW_ROUND") \
        or os.environ.get("WATCHER_ROUND")
    if not rnd:
        print("# window-relay skipped: neither G2VEC_BENCH_WINDOW_ROUND "
              "nor WATCHER_ROUND is set, so the current round is unknown "
              "(the watcher exports it from tools/ROUND)", file=sys.stderr,
              flush=True)
        return {}
    out = {}
    # (mtime, name): deterministic when a fresh checkout flattens mtimes —
    # BENCH_LOCAL_r05 < _r05b lexicographically matches window order.
    for path in sorted(_glob.glob(
            os.path.join(here, f"BENCH_LOCAL_{rnd}*.json")),
            key=lambda p: (os.path.getmtime(p), p)):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        for d in rec.get("lines", []):
            # Only direct chip measurements: a line that is itself a
            # relay, a host-side fallback line, or an artifact-carried
            # value (from_artifact) must not be re-relayed under a claim
            # of being chip-measured in that artifact.
            if isinstance(d, dict) and d.get("metric") \
                    and d.get("value") is not None \
                    and "chip_window_relay" not in d \
                    and "from_artifact" not in d \
                    and not d.get("chip_free_fallback"):
                out[d["metric"]] = (d, os.path.basename(path))
    return out


# Metrics whose measurement runs on the HOST even during a chip window
# (the native C++ sampler never touches the accelerator): a relay of one
# of these must not be stamped with chip provenance (ADVICE r5 #1/#3).
HOST_SIDE_METRICS = frozenset({
    "walker_native_walks_per_sec",
    "config2_walker_native_walks_per_sec",
})


def _relay_line(line: dict, artifact: str,
                reason: str = "no TPU backend is usable at driver bench "
                              "time") -> dict:
    host_side = (line.get("metric") in HOST_SIDE_METRICS
                 or bool(line.get("chip_free_fallback")))
    where = "measuring host, not the chip" if host_side else "real chip"
    return {**line, "chip_window_relay": artifact,
            "relay_measured_on": "host-cpu" if host_side else "tpu",
            "relay_note": "measured during the in-round chip window by "
                          f"the watcher battery (on the {where}; artifact "
                          f"committed at HEAD); relayed because {reason}"}


def _acceptance_relay_line(artifact_dir: "str | None" = None,
                           skip_reason: str =
                           "G2VEC_BENCH_SKIP_ACCEPT (dedicated watcher "
                           "stage owns the refresh)") -> dict:
    """The acceptance stage's carry line: when TPU_ACCEPTANCE.json was
    already produced AT THIS code state (by the dedicated watcher stage
    or an earlier bench run) its acc_val is carried into this record
    (with its source named) so the bench record stays self-contained;
    otherwise the honest skip with ``skip_reason``."""
    line = {"metric": "tpu_acceptance_acc_val", "value": None,
            "unit": "", "vs_baseline": None, "skipped": skip_reason}
    try:
        from tools.tpu_acceptance import _code_key

        here = artifact_dir or os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "TPU_ACCEPTANCE.json")) as f:
            art = json.load(f)
        if art.get("code_key") == _code_key() \
                and art.get("acc_val") is not None:
            ref_acc = art["reference_transcript"]["acc_val"]
            line = {"metric": "tpu_acceptance_acc_val",
                    "value": round(art["acc_val"], 4),
                    "unit": "ACC[val]",
                    "vs_baseline": round(art["acc_val"] / ref_acc, 3),
                    "n_paths": art.get("n_paths"),
                    "pipeline_wall_seconds":
                        art.get("pipeline_wall_seconds"),
                    "from_artifact": "TPU_ACCEPTANCE.json (dedicated "
                                     "watcher stage, code_key match)"}
    except Exception:  # noqa: BLE001 — fall back to the skip line
        pass
    return line


def _hostonly() -> None:
    """Child: chip-free metrics (native sampler vs the reference loop).
    MUST NOT import jax — see _hostonly_fallback."""
    from g2vec_tpu.ops.host_walker import edges_to_csr

    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    # Chip-free but real: the convergence metric is a property of the
    # committed acceptance history, not of this host's backend.
    print(json.dumps(_epochs_to_088_line()), flush=True)

    # Every chip-gated metric appears as its landed in-round chip-window
    # value (with relay provenance) when the watcher battery measured it,
    # else as an explicit honest null rather than being absent — the
    # round's artifact then lists the full armed surface (VERDICT r4:
    # metrics "never appeared in any committed bench artifact" when the
    # tunnel stayed dead).
    landed = _landed_window_lines(
        os.environ.get("G2VEC_BENCH_WINDOW_DIR") or None)
    for gated, unit in GATED_CHIP_METRICS:
        if gated in landed:
            print(json.dumps(_relay_line(*landed[gated])), flush=True)
            continue
        print(json.dumps({"metric": gated, "value": None, "unit": unit,
                          "vs_baseline": None,
                          "skipped": "chip-free round (no usable TPU "
                                     "backend); armed for the next chip "
                                     "window"}), flush=True)

    src, dst, w, n_genes = _load_bench_edges()
    csr = edges_to_csr(src, dst, w, n_genes)
    note(f"host-only network: {n_genes} genes, {src.size} edges")
    baseline, n_base = _reference_walk_baseline(*csr, n_genes, LEN_PATH)
    note(f"host reference loop: {baseline:.1f} walks/s "
         f"({n_base} stratified walks)")
    # BASELINE config #2's walker half (lenPath = 2x the default 80) is
    # host work — measurable with no chip. Its trainer half (hidden=512)
    # stays chip-gated in _measure. Emitted BEFORE the headline native
    # line: the driver's parsed field reads the LAST line.
    try:
        print(json.dumps(_native_walker_line(
            src, dst, w, n_genes, baseline, note,
            {"chip_free_fallback": True,
             "note": f"BASELINE config #2 walk shape (lenPath="
                     f"{2 * LEN_PATH}) on the native sampler; baseline = "
                     f"the reference loop at the DEFAULT lenPath on this "
                     f"host"},
            metric="config2_walker_native_walks_per_sec",
            len_path=2 * LEN_PATH)), flush=True)
    except Exception as e:  # noqa: BLE001 — headline line must still print
        print(json.dumps(
            {"metric": "config2_walker_native_walks_per_sec", "value": None,
             "unit": "walks/s", "vs_baseline": None,
             "len_path": 2 * LEN_PATH, "chip_free_fallback": True,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    # Stage-3 shape (7,523 genes) on the native sampler — chip-free
    # measurable, with its own reference-loop baseline on the SAME
    # restricted graph (the device twin stays chip-gated above).
    try:
        s_r, d_r, w_r, ng_r = _restrict_bench_edges(src, dst, w, n_genes)
        base_r, nb_r = _reference_walk_baseline(
            *edges_to_csr(s_r, d_r, w_r, ng_r), ng_r, LEN_PATH,
            budget_s=min(BASELINE_BUDGET, 8.0))
        note(f"restricted graph: {ng_r} genes, {s_r.size} edges; reference "
             f"loop {base_r:.1f} walks/s ({nb_r} walks)")
        print(json.dumps(_native_walker_line(
            s_r, d_r, w_r, ng_r, base_r, note,
            {"n_edges": int(s_r.size), "chip_free_fallback": True,
             "baseline_host_walks_per_sec": round(base_r, 2),
             "note": "stage-3 walk shape: bundled network restricted to "
                     "the transcript's 7,523-gene expression∩network set"},
            metric="walker_native_restricted_walks_per_sec",
            n_threads=_cli_sampler_threads())), flush=True)
    except Exception as e:  # noqa: BLE001 — headline line must still print
        print(json.dumps(
            {"metric": "walker_native_restricted_walks_per_sec",
             "value": None, "unit": "walks/s", "vs_baseline": None,
             "chip_free_fallback": True,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    # Sampler thread-scaling + bit-identity check (the --sampler-threads
    # breakdown): host work, chip-free measurable, printed BEFORE the
    # headline native line (the driver parses the last line).
    try:
        print(json.dumps({**_mt_speedup_line(src, dst, w, n_genes, note),
                          "chip_free_fallback": True}), flush=True)
    except Exception as e:  # noqa: BLE001 — headline line must still print
        print(json.dumps(
            {"metric": "walker_native_mt_speedup", "value": None,
             "unit": "x", "vs_baseline": None, "chip_free_fallback": True,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    # Batch-engine throughput A/B (runs/hour): live when armed, else the
    # committed artifact with provenance, else an honest null — before
    # the headline line either way (the driver parses the last line).
    try:
        print(json.dumps({**_batch_ab_hostonly_line(note),
                          "chip_free_fallback": True}), flush=True)
    except Exception as e:  # noqa: BLE001 — headline line must still print
        print(json.dumps(
            {"metric": "batch_runs_per_hour", "value": None,
             "unit": "runs/h", "vs_baseline": None,
             "chip_free_fallback": True,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    # Scenario-engine throughput A/B (runs/hour): live when armed, else
    # the committed artifact with provenance, else an honest null.
    try:
        print(json.dumps({**_scenario_ab_hostonly_line(note),
                          "chip_free_fallback": True}), flush=True)
    except Exception as e:  # noqa: BLE001 — headline line must still print
        print(json.dumps(
            {"metric": "scenario_runs_per_hour", "value": None,
             "unit": "runs/h", "vs_baseline": None,
             "chip_free_fallback": True,
             "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    line = _native_walker_line(
        src, dst, w, n_genes, baseline, note,
        {"baseline_host_walks_per_sec": round(baseline, 2),
         "chip_free_fallback": True,
         "note": "threaded C++ CSR sampler (ops/host_walker.py), the "
                 "default single-host stage-3 backend; baseline = the "
                 "reference's own walk loop on this host. Measured with NO "
                 "usable jax backend this round."},
        n_threads=_cli_sampler_threads())
    print(json.dumps(line), flush=True)
    # The driver records the LAST line as "the result": when the watcher
    # battery landed the headline train metric on the real chip earlier
    # this round, the round's record must lead with it (with relay
    # provenance), not with the host walker number.
    headline = landed.get("cbow_train_paths_per_sec_per_chip")
    if headline:
        print(json.dumps(_relay_line(*headline)), flush=True)


def _batch_ab_line(note) -> dict:
    """Batched-vs-sequential runs/hour A/B — the batch engine's headline.

    Sequential baseline = the PRE-ENGINE workflow for N validation runs:
    one fresh ``python -m g2vec_tpu`` process per variant (each re-pays
    interpreter+jax startup and every XLA compile, with the device idle
    between jobs — exactly the N-runs-cost-Nx shape the engine exists to
    kill). Batched side = ONE process running the same N variants as a
    ``--seeds N`` manifest. Both sides min-of-``BATCH_AB_REPS``; the
    variants are the amortized seed sweep (train/k-means seeds vary, one
    shared walk product), at tiny CPU-safe synthetic shapes
    (env-shrinkable like the PR 4 nets). On-the-spot honesty check: the
    batched lanes' output files must be BYTE-IDENTICAL to the sequential
    runs' — a speedup that changes results would be worthless.

    Runs with no jax in THIS process (children import it); usable from
    the --_hostonly child.
    """
    import shutil
    import tempfile

    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    repo = os.path.dirname(os.path.abspath(__file__))
    n, reps = BATCH_AB_VARIANTS, BATCH_AB_REPS
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}

    def child(args, timeout=600):
        proc = subprocess.run([sys.executable, "-m", "g2vec_tpu"] + args,
                              capture_output=True, text=True, env=env,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"bench batch child rc={proc.returncode}: "
                f"{(proc.stderr or proc.stdout)[-400:]}")

    with tempfile.TemporaryDirectory() as td:
        spec = SyntheticSpec(
            n_good=24, n_poor=20, module_size=12 * BATCH_AB_SCALE,
            n_background=24 * BATCH_AB_SCALE, n_expr_only=4, n_net_only=4,
            module_chords=2, background_edges=40 * BATCH_AB_SCALE, seed=7)
        paths = write_synthetic_tsv(spec, td)
        base = [paths["expression"], paths["clinical"], paths["network"],
                "RESULT", "-p", "8", "-r", "2", "-s", "16",
                "-e", str(BATCH_AB_EPOCHS), "-l", "0.05", "-n", "5",
                "--compute-dtype", "float32", "--platform", "cpu",
                "--seed", "0"]

        def seq_rep(rep: int) -> float:
            out = os.path.join(td, f"seq{rep}")
            os.makedirs(out, exist_ok=True)
            t0 = time.time()
            for k in range(n):
                args = list(base)
                args[3] = os.path.join(out, f"s{k}")
                child(args + ["--train-seed", str(k),
                              "--kmeans-seed", str(k)])
            return time.time() - t0

        def bat_rep(rep: int):
            out = os.path.join(td, f"bat{rep}")
            os.makedirs(out, exist_ok=True)
            args = list(base)
            args[3] = os.path.join(out, "m")
            mj = os.path.join(out, "metrics.jsonl")
            t0 = time.time()
            child(args + ["--seeds", str(n), "--metrics-jsonl", mj])
            wall = time.time() - t0
            done = {}
            with open(mj) as f:
                for line in f:
                    ev = json.loads(line)
                    if ev.get("event") == "done" and "lane" not in ev:
                        done = ev
            return wall, done

        seq_walls, bat_walls, done = [], [], {}
        for rep in range(reps):
            seq_walls.append(seq_rep(rep))
            note(f"batch A/B rep {rep}: sequential {n} runs in "
                 f"{seq_walls[-1]:.1f}s")
            wall, done = bat_rep(rep)
            bat_walls.append(wall)
            note(f"batch A/B rep {rep}: batched {n} lanes in {wall:.1f}s")
        # Honesty check on the LAST rep's artifacts: every lane file ==
        # the sequential twin's file.
        identical = True
        for k in range(n):
            for suffix in ("biomarkers", "lgroups", "vectors"):
                fa = os.path.join(td, f"seq{reps - 1}", f"s{k}_{suffix}.txt")
                fb = os.path.join(td, f"bat{reps - 1}",
                                  f"m.s{k}_{suffix}.txt")
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        identical = False
                        note(f"batch A/B MISMATCH: lane s{k} {suffix}")
        shutil.rmtree(td, ignore_errors=True)

    seq_rph = n / min(seq_walls) * 3600.0
    bat_rph = n / min(bat_walls) * 3600.0
    return {
        "metric": "batch_runs_per_hour", "value": round(bat_rph, 1),
        "unit": "runs/h", "vs_baseline": round(bat_rph / seq_rph, 2),
        "sequential_runs_per_hour": round(seq_rph, 1),
        "sequential_wall_s": round(min(seq_walls), 2),
        "batched_wall_s": round(min(bat_walls), 2),
        "lanes": n, "reps": reps, "epochs": BATCH_AB_EPOCHS,
        "scale": BATCH_AB_SCALE, "bit_identical": identical,
        "walk_stats": done.get("walk_stats"),
        "buckets": done.get("buckets"),
        "sequential_mode": "one fresh process per run (re-paid "
                           "imports+compiles, device idle between jobs — "
                           "the pre-engine repeated-validation workflow)",
        "note": "amortized --seeds sweep: train/kmeans seeds vary, ONE "
                "shared stage-3 walk product; lane outputs verified "
                "byte-identical to the sequential runs on the spot",
    }


def _batch_ab_hostonly_line(note) -> dict:
    """The batch A/B's appearance in a --_hostonly round: measured live
    when G2VEC_BENCH_BATCH_AB=1 (several minutes of children), else
    relayed from the committed BENCH_BATCH_AB.json artifact (produced by
    ``bench.py --_batch_ab``) with provenance, else an explicit honest
    null naming the arming command."""
    if os.environ.get("G2VEC_BENCH_BATCH_AB") == "1":
        return _batch_ab_line(note)
    art_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            BATCH_AB_ARTIFACT)
    try:
        with open(art_path) as f:
            art = json.load(f)
        line = dict(art["line"])
        line["from_artifact"] = (
            f"{BATCH_AB_ARTIFACT} (code_key {art.get('code_key')}; rerun "
            f"'python bench.py --_batch_ab' to refresh)")
        return line
    except (OSError, ValueError, KeyError):
        return {"metric": "batch_runs_per_hour", "value": None,
                "unit": "runs/h", "vs_baseline": None,
                "error": "no committed BENCH_BATCH_AB.json and "
                         "G2VEC_BENCH_BATCH_AB unset; arm with "
                         "'python bench.py --_batch_ab'"}


def _batch_ab() -> None:
    """Standalone mode: measure the batch A/B and (with
    G2VEC_BENCH_BATCH_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _batch_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_BATCH_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, BATCH_AB_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_batch_ab"}, f, indent=1)
        note(f"wrote {BATCH_AB_ARTIFACT}")


def _scenario_ab_line(note) -> dict:
    """Scenario-engine throughput A/B — the stats/ subsystem's headline.

    Sequential baseline = the PRE-ENGINE stability study: one fresh
    ``python -m g2vec_tpu`` process per bootstrap replicate, each handed
    its resample seed by hand (``--subsample-mode bootstrap
    --subsample-seed <derived>``) — exactly what a careful user would
    script today, and exactly the N-runs-cost-Nx shape the scenario
    engine kills. Scenario side = ONE ``--scenario bootstrap
    --replicates N`` process: same replicates as shape-bucketed lanes
    sharing stages 1-2 and compiles, plus the reduction. Both sides
    min-of-``SCN_AB_REPS``. On-the-spot honesty check: every scenario
    lane's three output files must be BYTE-IDENTICAL to its sequential
    solo twin's (the seeds are the same derive_seed tree on both sides).

    Runs with no jax in THIS process (children import it); usable from
    the --_hostonly child.
    """
    import shutil
    import tempfile

    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.stats.plan import derive_seed

    repo = os.path.dirname(os.path.abspath(__file__))
    n, reps, seed_root = SCN_AB_REPLICATES, SCN_AB_REPS, 7
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}

    def child(args, timeout=600):
        proc = subprocess.run([sys.executable, "-m", "g2vec_tpu"] + args,
                              capture_output=True, text=True, env=env,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"bench scenario child rc={proc.returncode}: "
                f"{(proc.stderr or proc.stdout)[-400:]}")

    with tempfile.TemporaryDirectory() as td:
        spec = SyntheticSpec(
            n_good=24, n_poor=20, module_size=12 * SCN_AB_SCALE,
            n_background=24 * SCN_AB_SCALE, n_expr_only=4, n_net_only=4,
            module_chords=2, background_edges=40 * SCN_AB_SCALE, seed=7)
        paths = write_synthetic_tsv(spec, td)
        base = [paths["expression"], paths["clinical"], paths["network"],
                "RESULT", "-p", "8", "-r", "2", "-s", "16",
                "-e", str(SCN_AB_EPOCHS), "-l", "0.05", "-n", "5",
                "--compute-dtype", "float32", "--platform", "cpu",
                "--seed", "0"]

        def seq_rep(rep: int) -> float:
            out = os.path.join(td, f"seq{rep}")
            os.makedirs(out, exist_ok=True)
            t0 = time.time()
            for r in range(n):
                args = list(base)
                args[3] = os.path.join(out, f"s{r}")
                child(args + ["--subsample-mode", "bootstrap",
                              "--patient-subsample", "1.0",
                              "--subsample-seed",
                              str(derive_seed(seed_root, r, "bootstrap"))])
            return time.time() - t0

        def scn_rep(rep: int) -> float:
            out = os.path.join(td, f"scn{rep}")
            os.makedirs(out, exist_ok=True)
            args = list(base)
            args[3] = os.path.join(out, "m")
            t0 = time.time()
            child(args + ["--scenario", "bootstrap", "--replicates",
                          str(n), "--scenario-seed", str(seed_root)])
            return time.time() - t0

        seq_walls, scn_walls = [], []
        for rep in range(reps):
            seq_walls.append(seq_rep(rep))
            note(f"scenario A/B rep {rep}: sequential {n} replicates in "
                 f"{seq_walls[-1]:.1f}s")
            scn_walls.append(scn_rep(rep))
            note(f"scenario A/B rep {rep}: one scenario process in "
                 f"{scn_walls[-1]:.1f}s")
        # Honesty check on the LAST rep: every scenario lane's files ==
        # its hand-seeded sequential twin's, byte for byte.
        identical = True
        for r in range(n):
            for suffix in ("biomarkers", "lgroups", "vectors"):
                fa = os.path.join(td, f"seq{reps - 1}",
                                  f"s{r}_{suffix}.txt")
                fb = os.path.join(td, f"scn{reps - 1}",
                                  f"m.b{r:03d}_{suffix}.txt")
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        identical = False
                        note(f"scenario A/B MISMATCH: replicate {r} "
                             f"{suffix}")
        stability = os.path.exists(os.path.join(
            td, f"scn{reps - 1}", "m_stability.txt"))
        shutil.rmtree(td, ignore_errors=True)

    seq_rph = n / min(seq_walls) * 3600.0
    scn_rph = n / min(scn_walls) * 3600.0
    return {
        "metric": "scenario_runs_per_hour", "value": round(scn_rph, 1),
        "unit": "runs/h", "vs_baseline": round(scn_rph / seq_rph, 2),
        "sequential_runs_per_hour": round(seq_rph, 1),
        "sequential_wall_s": round(min(seq_walls), 2),
        "scenario_wall_s": round(min(scn_walls), 2),
        "replicates": n, "reps": reps, "epochs": SCN_AB_EPOCHS,
        "scale": SCN_AB_SCALE, "bit_identical": identical,
        "stability_artifact": stability,
        "sequential_mode": "one fresh process per bootstrap replicate, "
                           "seeds derived by hand (the pre-engine "
                           "stability-study workflow)",
        "note": "--scenario bootstrap: same derive_seed tree both sides; "
                "lane outputs verified byte-identical to the hand-seeded "
                "sequential replicates on the spot",
    }


def _scenario_ab_hostonly_line(note) -> dict:
    """The scenario A/B's appearance in a --_hostonly round: measured
    live when G2VEC_BENCH_SCN_AB=1 (several minutes of children), else
    relayed from the committed BENCH_SCENARIO_AB.json artifact (produced
    by ``bench.py --_scenario_ab``) with provenance, else an explicit
    honest null naming the arming command."""
    if os.environ.get("G2VEC_BENCH_SCN_AB") == "1":
        return _scenario_ab_line(note)
    art_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            SCN_AB_ARTIFACT)
    try:
        with open(art_path) as f:
            art = json.load(f)
        line = dict(art["line"])
        line["from_artifact"] = (
            f"{SCN_AB_ARTIFACT} (code_key {art.get('code_key')}; rerun "
            f"'python bench.py --_scenario_ab' to refresh)")
        return line
    except (OSError, ValueError, KeyError):
        return {"metric": "scenario_runs_per_hour", "value": None,
                "unit": "runs/h", "vs_baseline": None,
                "error": "no committed BENCH_SCENARIO_AB.json and "
                         "G2VEC_BENCH_SCN_AB unset; arm with "
                         "'python bench.py --_scenario_ab'"}


def _scenario_ab() -> None:
    """Standalone mode: measure the scenario A/B and (with
    G2VEC_BENCH_SCN_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _scenario_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_SCN_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, SCN_AB_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_scenario_ab"}, f,
                      indent=1)
        note(f"wrote {SCN_AB_ARTIFACT}")


#: Child wrapper for the stream A/B: run the CLI in-process and report the
#: child's own peak RSS (RUSAGE_SELF ru_maxrss is per-process and exact —
#: RUSAGE_CHILDREN in the parent is a monotone max over ALL children and
#: cannot attribute a peak to one arm).
_STREAM_RSS_WRAPPER = (
    "import sys, resource\n"
    "from g2vec_tpu.__main__ import main\n"
    "rc = main(sys.argv[1:])\n"
    "print('G2V_RSS_KB=%d'\n"
    "      % resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)\n"
    "sys.exit(rc)\n")


def _stream_child(args, env, timeout=1800) -> int:
    """Run one pipeline child; returns its peak RSS in KB."""
    proc = subprocess.run([sys.executable, "-c", _STREAM_RSS_WRAPPER] + args,
                          capture_output=True, text=True, env=env,
                          timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"stream A/B child rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("G2V_RSS_KB="):
            return int(line.split("=", 1)[1])
    raise RuntimeError("stream A/B child printed no RSS line")


def _stream_last_events(mj_path: str) -> dict:
    """Last event of each type from a metrics JSONL stream."""
    out = {}
    with open(mj_path) as f:
        for line in f:
            ev = json.loads(line)
            out[ev.get("event")] = ev
    return out


def _stream_arm(tmpdir: str, tag: str, base_args, extra, env, reps,
                note) -> dict:
    """min-of-``reps`` wall for one (input, mode) arm; keeps the best
    rep's metrics, RSS, and output files."""
    best = None
    for rep in range(reps):
        out = os.path.join(tmpdir, f"{tag}-r{rep}")
        os.makedirs(out, exist_ok=True)
        mj = os.path.join(out, "metrics.jsonl")
        args = list(base_args)
        args[3] = os.path.join(out, "RES")
        args += ["--metrics-jsonl", mj] + list(extra)
        t0 = time.time()
        rss_kb = _stream_child(args, env)
        wall = time.time() - t0
        note(f"stream A/B {tag} rep {rep}: {wall:.1f}s rss {rss_kb//1024}MB")
        if best is None or wall < best["wall_s"]:
            evs = _stream_last_events(mj)
            best = {
                "wall_s": round(wall, 2), "rss_kb": rss_kb,
                "acc_val": (evs.get("train_done") or {}).get("acc_val"),
                "stage_seconds": (evs.get("done") or {}).get(
                    "stage_seconds", {}),
                "stream": {k: v for k, v in (evs.get("stream") or {}).items()
                           if k not in ("seq", "ts", "event")},
                "result": os.path.join(out, "RES"),
            }
    return best


def _biomarker_overlap(res_a: str, res_b: str) -> "float | None":
    try:
        def genes(path):
            with open(path + "_biomarkers.txt") as f:
                return {l.strip() for l in f.readlines()[1:] if l.strip()}
        a, b = genes(res_a), genes(res_b)
        return round(len(a & b) / max(len(a), 1), 3)
    except OSError:
        return None


def _stream_ab_line(note) -> dict:
    """Streaming-vs-full-batch trainer A/B — the streaming mode's headline.

    Three claims, measured (fresh process per arm so peak RSS attributes
    cleanly):

    (a) **Overlap**: at bundled scale (the medium example-shaped
        synthetic), the streaming arm's time-to-first-update is a small
        fraction of the FULL arm's whole stage-3 wall — training starts
        while sampling runs, instead of after it.
    (b) **Bounded memory**: on the scale-free big graph (data/synth.py),
        the walk-path volume grows ~3x across the STREAM_AB_WALK_REPS
        axis; the full arm's peak RSS grows with it (it materializes and
        densifies every path), the streaming arm's stays ~flat
        (O(shard x ring depth) paths in flight).
    (c) **No wall regression at bundled scale**: streaming end-to-end
        wall within noise of full-batch (ratio reported).

    Parity is reported beside the perf numbers (val-ACC delta + top-N
    biomarker overlap): the contract is the statistical band
    tests/test_stream.py pins, not bitwise equality.
    """
    import shutil
    import tempfile

    from g2vec_tpu.data.make_example import SCALES
    from g2vec_tpu.data.synth import SynthGraphSpec, write_synth_graph
    from g2vec_tpu.data.synthetic import write_synthetic_tsv

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    reps = STREAM_AB_REPS
    big_reps = int(os.environ.get("G2VEC_BENCH_STREAM_BIG_REPS", "1"))
    line: dict = {"metric": "stream_time_to_first_update_ms", "unit": "ms"}
    with tempfile.TemporaryDirectory() as td:
        # ---- bundled scale: the medium example-shaped synthetic ----
        paths = write_synthetic_tsv(SCALES["medium"],
                                    os.path.join(td, "data"))
        base = [paths["expression"], paths["clinical"], paths["network"],
                "RES", "-p", "20", "-r", "10", "-s", "32",
                "-e", str(STREAM_AB_EPOCHS), "-n", "20",
                "--compute-dtype", "float32", "--platform", "cpu",
                "--seed", "5"]
        full = _stream_arm(td, "bundled-full", base, [], env, reps, note)
        stream = _stream_arm(
            td, "bundled-stream", base,
            ["--train-mode", "streaming", "--shard-paths", "2048"],
            env, reps, note)
        ttfu_ms = stream["stream"].get("time_to_first_update_ms")
        full_paths_wall = full["stage_seconds"].get("paths")
        overlap_frac = (round(ttfu_ms / (full_paths_wall * 1e3), 3)
                        if ttfu_ms and full_paths_wall else None)
        line.update({
            "value": ttfu_ms,
            "full_stage3_wall_s": full_paths_wall,
            "ttfu_frac_of_full_stage3": overlap_frac,
            "overlap_ok": (overlap_frac is not None
                           and overlap_frac < 0.5),
            "bundled_full_wall_s": full["wall_s"],
            "bundled_stream_wall_s": stream["wall_s"],
            "bundled_wall_ratio": round(stream["wall_s"] / full["wall_s"],
                                        3),
            "bundled_full_rss_mb": full["rss_kb"] // 1024,
            "bundled_stream_rss_mb": stream["rss_kb"] // 1024,
            "bundled_runs_per_hour": {
                "full": round(3600.0 / full["wall_s"], 1),
                "streaming": round(3600.0 / stream["wall_s"], 1)},
            "parity": {
                "acc_val_full": full["acc_val"],
                "acc_val_streaming": stream["acc_val"],
                "acc_val_delta": (round(stream["acc_val"] - full["acc_val"],
                                        4)
                                  if None not in (stream["acc_val"],
                                                  full["acc_val"])
                                  else None),
                "biomarker_overlap": _biomarker_overlap(full["result"],
                                                        stream["result"]),
            },
            "bundled_stream_stats": stream["stream"],
        })
        # ---- big graph: path volume grows, streaming RSS must not ----
        growth = {}
        for walk_reps in STREAM_AB_WALK_REPS:
            spec = SynthGraphSpec(n_genes=STREAM_AB_GENES, seed=3)
            gdir = os.path.join(td, f"big{walk_reps}")
            gp = write_synth_graph(spec, gdir)
            gbase = [gp["expression"], gp["clinical"], gp["network"],
                     "RES", "-p", "16", "-r", str(walk_reps), "-s", "32",
                     "-e", str(STREAM_AB_BIG_EPOCHS), "-n", "20",
                     "--compute-dtype", "float32", "--platform", "cpu",
                     "--seed", "5"]
            gfull = _stream_arm(td, f"big{walk_reps}-full", gbase, [],
                                env, big_reps, note)
            gstream = _stream_arm(
                td, f"big{walk_reps}-stream", gbase,
                ["--train-mode", "streaming", "--shard-paths", "2048"],
                env, big_reps, note)
            growth[f"walk_reps_{walk_reps}"] = {
                "full_rss_mb": gfull["rss_kb"] // 1024,
                "stream_rss_mb": gstream["rss_kb"] // 1024,
                "full_wall_s": gfull["wall_s"],
                "stream_wall_s": gstream["wall_s"],
                "stream_ttfu_ms": gstream["stream"].get(
                    "time_to_first_update_ms"),
                "full_stage3_wall_s": gfull["stage_seconds"].get("paths"),
                "stream_ring_peak_bytes": gstream["stream"].get(
                    "ring_peak_bytes"),
                "rows_sampled": gstream["stream"].get("rows_sampled"),
            }
        lo, hi = (f"walk_reps_{STREAM_AB_WALK_REPS[0]}",
                  f"walk_reps_{STREAM_AB_WALK_REPS[-1]}")
        line["big_graph"] = {
            "genes": STREAM_AB_GENES, "epochs": STREAM_AB_BIG_EPOCHS,
            **growth,
            "full_rss_growth_mb": (growth[hi]["full_rss_mb"]
                                   - growth[lo]["full_rss_mb"]),
            "stream_rss_growth_mb": (growth[hi]["stream_rss_mb"]
                                     - growth[lo]["stream_rss_mb"]),
        }
        shutil.rmtree(td, ignore_errors=True)
    line["reps"] = reps
    line["note"] = (
        "fresh process per arm; RSS = child ru_maxrss. Streaming contract "
        "is statistical (val-ACC band + biomarker overlap, pinned in "
        "tests/test_stream.py); full-batch stays the bitwise-golden path")
    return line


def _stream_ab() -> None:
    """Standalone mode: measure the streaming A/B and (with
    G2VEC_BENCH_STREAM_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _stream_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_STREAM_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, STREAM_AB_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_stream_ab"}, f, indent=1)
        note(f"wrote {STREAM_AB_ARTIFACT}")


def _serve_ab_line(note) -> dict:
    """Resident-daemon-vs-fresh-process A/B under Poisson job arrivals —
    the serve subsystem's headline.

    Both arms see the SAME seeded arrival schedule (exponential
    interarrivals, mean ``SERVE_AB_MEAN_ARRIVAL_S``) of N single-run jobs
    (train/k-means seed k — shape-compatible, so the daemon's scheduler
    may join backed-up jobs into one lane bucket). Baseline = the
    pre-serve workflow: a fresh ``python -m g2vec_tpu`` process per job,
    FIFO on the one device (each re-pays interpreter+jax startup and
    every compile; latency includes queue wait). Served = ONE daemon
    owning the device and every warm cache; jobs stream over its socket.
    Reported from the best of ``SERVE_AB_REPS`` reps per arm: sustained
    runs/hour over the window (first arrival -> last completion) and the
    p50/p99 of per-job latency (completion - arrival). On-the-spot
    honesty check: every served job's output files must be BYTE-IDENTICAL
    to the fresh-process baseline's — the daemon's whole contract.

    Runs with no jax in THIS process (daemon and children import it).
    """
    import random
    import shutil
    import tempfile
    import threading

    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client as sclient

    repo = os.path.dirname(os.path.abspath(__file__))
    n, reps, epochs = SERVE_AB_JOBS, SERVE_AB_REPS, SERVE_AB_EPOCHS
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    rng = random.Random(0)
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / SERVE_AB_MEAN_ARRIVAL_S)

    def _pct(lat, q):
        s = sorted(lat)
        return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)

    with tempfile.TemporaryDirectory() as td:
        spec = SyntheticSpec(
            n_good=24, n_poor=20, module_size=12 * SERVE_AB_SCALE,
            n_background=24 * SERVE_AB_SCALE, n_expr_only=4, n_net_only=4,
            module_chords=2, background_edges=40 * SERVE_AB_SCALE, seed=7)
        paths = write_synthetic_tsv(spec, td)
        base_args = [paths["expression"], paths["clinical"],
                     paths["network"], "RESULT", "-p", "8", "-r", "2",
                     "-s", "16", "-e", str(epochs), "-l", "0.05", "-n", "5",
                     "--compute-dtype", "float32", "--platform", "cpu",
                     "--seed", "0"]
        job_base = {"expression_file": paths["expression"],
                    "clinical_file": paths["clinical"],
                    "network_file": paths["network"],
                    "lenPath": 8, "numRepetition": 2, "sizeHiddenlayer": 16,
                    "epoch": epochs, "learningRate": 0.05, "numBiomarker": 5,
                    "compute_dtype": "float32", "seed": 0}

        def solo_child(result: str, k: int) -> None:
            args = list(base_args)
            args[3] = result
            proc = subprocess.run(
                [sys.executable, "-m", "g2vec_tpu"] + args
                + ["--train-seed", str(k), "--kmeans-seed", str(k)],
                capture_output=True, text=True, env=env, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"serve A/B solo child rc={proc.returncode}: "
                    f"{(proc.stderr or proc.stdout)[-400:]}")

        def window_stats(done):
            lat = [done[k] - arrivals[k] for k in range(n)]
            window = max(done) - arrivals[0]
            return n / window * 3600.0, lat

        def baseline_rep(rep: int):
            out = os.path.join(td, f"base{rep}")
            os.makedirs(out, exist_ok=True)
            done = [0.0] * n
            t0 = time.time()
            for k in range(n):
                now = time.time() - t0
                if now < arrivals[k]:
                    time.sleep(arrivals[k] - now)
                solo_child(os.path.join(out, f"job{k}"), k)
                done[k] = time.time() - t0
            return window_stats(done)

        def served_rep(rep: int):
            out = os.path.join(td, f"serve{rep}")
            os.makedirs(out, exist_ok=True)
            sock = os.path.join(td, f"s{rep}.sock")
            log = open(os.path.join(out, "daemon.log"), "w")
            daemon = subprocess.Popen(
                [sys.executable, "-m", "g2vec_tpu", "serve",
                 "--socket", sock,
                 "--state-dir", os.path.join(out, "state"),
                 "--platform", "cpu",
                 "--cache-dir", os.path.join(out, "cache"),
                 "--max-join", "8"],
                env=env, stdout=log, stderr=subprocess.STDOUT)
            try:
                if not sclient.wait_ready(sock, 120):
                    raise RuntimeError("serve daemon never became ready "
                                       f"(log: {log.name})")
                done = [0.0] * n
                errs: list = []
                t0 = time.time()

                def submit(k: int) -> None:
                    try:
                        evs = sclient.submit_job(
                            sock, {**job_base,
                                   "result_name": os.path.join(
                                       out, f"job{k}"),
                                   "train_seed": k, "kmeans_seed": k})
                        if evs[-1].get("event") != "job_done":
                            errs.append(f"job{k}: {evs[-1]}")
                        done[k] = time.time() - t0
                    except Exception as e:  # noqa: BLE001 — reported below
                        errs.append(f"job{k}: {type(e).__name__}: {e}")

                threads = []
                for k in range(n):
                    now = time.time() - t0
                    if now < arrivals[k]:
                        time.sleep(arrivals[k] - now)
                    th = threading.Thread(target=submit, args=(k,))
                    th.start()
                    threads.append(th)
                for th in threads:
                    th.join()
                if errs:
                    raise RuntimeError("serve A/B job failure(s): "
                                       + "; ".join(errs[:3]))
                return window_stats(done)
            finally:
                try:
                    sclient.shutdown(sock)
                except OSError:
                    pass
                try:
                    daemon.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    daemon.kill()
                log.close()

        base_best, serve_best = None, None
        for rep in range(reps):
            rph_b, lat_b = baseline_rep(rep)
            note(f"serve A/B rep {rep}: baseline {n} jobs "
                 f"-> {rph_b:.0f} runs/h (p50 {_pct(lat_b, 0.5)}s)")
            if base_best is None or rph_b > base_best[0]:
                base_best = (rph_b, lat_b)
            rph_s, lat_s = served_rep(rep)
            note(f"serve A/B rep {rep}: served   {n} jobs "
                 f"-> {rph_s:.0f} runs/h (p50 {_pct(lat_s, 0.5)}s)")
            if serve_best is None or rph_s > serve_best[0]:
                serve_best = (rph_s, lat_s)

        # Honesty check on the LAST rep's artifacts: every served job's
        # three files == the fresh-process baseline twin's, byte for byte.
        identical = True
        for k in range(n):
            for suffix in ("biomarkers", "lgroups", "vectors"):
                fa = os.path.join(td, f"base{reps - 1}",
                                  f"job{k}_{suffix}.txt")
                fb = os.path.join(td, f"serve{reps - 1}",
                                  f"job{k}.v_{suffix}.txt")
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        identical = False
                        note(f"serve A/B MISMATCH: job{k} {suffix}")
        shutil.rmtree(td, ignore_errors=True)

    rph_base, lat_base = base_best
    rph_serve, lat_serve = serve_best
    return {
        "metric": "serve_runs_per_hour", "value": round(rph_serve, 1),
        "unit": "runs/h", "vs_baseline": round(rph_serve / rph_base, 2),
        "baseline_runs_per_hour": round(rph_base, 1),
        "p50_latency_s": _pct(lat_serve, 0.5),
        "p99_latency_s": _pct(lat_serve, 0.99),
        "baseline_p50_latency_s": _pct(lat_base, 0.5),
        "baseline_p99_latency_s": _pct(lat_base, 0.99),
        "jobs": n, "reps": reps, "epochs": epochs,
        "mean_interarrival_s": SERVE_AB_MEAN_ARRIVAL_S,
        "scale": SERVE_AB_SCALE, "bit_identical": identical,
        "arrival_model": "seeded Poisson (exponential interarrivals), "
                         "identical schedule both arms; window = first "
                         "arrival -> last completion",
        "baseline_mode": "fresh python -m g2vec_tpu process per job, FIFO "
                         "on the device (re-paid imports+compiles per job, "
                         "latency includes queue wait — the pre-serve "
                         "workflow)",
        "note": "one resident daemon owns the device: warm jit/XLA/walk "
                "caches across jobs, shape-compatible backed-up jobs join "
                "one lane bucket; served outputs verified byte-identical "
                "to the fresh-process baseline on the spot",
    }


def _serve_ab() -> None:
    """Standalone mode: measure the serve A/B and (with
    G2VEC_BENCH_SERVE_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _serve_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_SERVE_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, SERVE_AB_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_serve_ab"}, f, indent=1)
        note(f"wrote {SERVE_AB_ARTIFACT}")


def _chaos_soak_line(note) -> dict:
    """Run tools/chaos_soak.py as a subprocess (no jax in THIS process)
    and distill its summary into one metric line. The soak's own exit
    code IS the acceptance: 0 iff every acknowledged job landed in
    exactly one terminal state with zero lost/duplicated and sampled
    byte parity intact."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": str(CHAOS_JOBS),
           "G2V_CHAOS_SEED": str(CHAOS_SEED),
           "G2V_CHAOS_BUDGET": str(CHAOS_BUDGET)}
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_soak.py")],
        capture_output=True, text=True, env=env,
        timeout=CHAOS_BUDGET + 120)
    for ln in (proc.stderr or "").splitlines():
        if ln.startswith("# "):
            note(f"chaos {ln[2:]}")
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        raise RuntimeError(
            f"chaos soak emitted no summary (rc={proc.returncode}): "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    accepted = summary.get("accepted", 0) or 1
    accounted = accepted - len(summary.get("lost", ()))
    return {
        "metric": "chaos_soak_accounted_fraction",
        "value": round(accounted / accepted, 4), "unit": "fraction",
        "ok": bool(summary.get("ok")) and proc.returncode == 0,
        "jobs": summary.get("jobs"), "accepted": accepted,
        "terminal_by_status": summary.get("terminal_by_status"),
        "lost": len(summary.get("lost", ())),
        "duplicated": len(summary.get("duplicated", ())),
        "kills": summary.get("kills"), "drains": summary.get("drains"),
        "drain_exit_codes": summary.get("drain_exit_codes"),
        "fault_injections": summary.get("fault_injections"),
        "cancels_sent": summary.get("cancels_sent"),
        "recover_p50_s": summary.get("recover_p50_s"),
        "recover_p99_s": summary.get("recover_p99_s"),
        "byte_checked": summary.get("byte_checked"),
        "byte_identical": summary.get("byte_identical"),
        "seed": summary.get("seed"),
        "wall_s": round(time.time() - t0, 1),
        "note": "seeded fault storm vs serve daemon (SIGKILL / SIGTERM "
                "drain / armed fault plans at stream_ckpt, train, drain "
                "seams / cancels / deadlines); acceptance = exactly-once "
                "terminal accounting + sampled byte parity vs solo "
                "uninterrupted twins",
    }


def _chaos_soak() -> None:
    """Standalone mode: run the chaos soak and (with
    G2VEC_BENCH_CHAOS_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _chaos_soak_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_CHAOS_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, CHAOS_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_chaos_soak"}, f,
                      indent=1)
        note(f"wrote {CHAOS_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _router_chaos_line(note) -> dict:
    """Router-mode chaos soak: tools/chaos_soak.py --replicas N as a
    subprocess. Acceptance = fleet-wide exactly-once accounting (every
    acked job exactly one terminal event across all replicas + one
    result record), sampled byte parity vs solo twins, drain rc 0, and
    the replica-death-to-first-requeued-job latency distribution from
    the router's failover events."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": str(ROUTER_CHAOS_JOBS),
           "G2V_CHAOS_REPLICAS": str(ROUTER_CHAOS_REPLICAS),
           "G2V_CHAOS_SEED": str(ROUTER_CHAOS_SEED),
           "G2V_CHAOS_BUDGET": str(ROUTER_CHAOS_BUDGET)}
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_soak.py")],
        capture_output=True, text=True, env=env,
        timeout=ROUTER_CHAOS_BUDGET + 180)
    for ln in (proc.stderr or "").splitlines():
        if ln.startswith("# "):
            note(f"router-chaos {ln[2:]}")
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        raise RuntimeError(
            f"router chaos soak emitted no summary "
            f"(rc={proc.returncode}): "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    accepted = summary.get("accepted", 0) or 1
    accounted = accepted - len(summary.get("lost", ()))
    return {
        "metric": "router_chaos_accounted_fraction",
        "value": round(accounted / accepted, 4), "unit": "fraction",
        "ok": bool(summary.get("ok")) and proc.returncode == 0,
        "jobs": summary.get("jobs"),
        "replicas": summary.get("replicas"), "accepted": accepted,
        "terminal_by_status": summary.get("terminal_by_status"),
        "lost": len(summary.get("lost", ())),
        "duplicated": len(summary.get("duplicated", ())),
        "replica_kills": summary.get("replica_kills"),
        "replica_drains": summary.get("replica_drains"),
        "router_restarts": summary.get("router_restarts"),
        "drain_exit_codes": summary.get("drain_exit_codes"),
        "cancels_sent": summary.get("cancels_sent"),
        "failovers": summary.get("failovers"),
        "requeue_p50_s": summary.get("requeue_p50_s"),
        "requeue_p99_s": summary.get("requeue_p99_s"),
        "router_restart_p99_s": summary.get("router_restart_p99_s"),
        "byte_checked": summary.get("byte_checked"),
        "byte_identical": summary.get("byte_identical"),
        "seed": summary.get("seed"),
        "wall_s": round(time.time() - t0, 1),
        "note": "seeded storm vs the replicated serve fleet (replica "
                "SIGKILL with router-driven fence/migrate/relaunch, "
                "synchronous replica drains, router SIGKILL+restart "
                "with live-replica adoption); acceptance = fleet-wide "
                "exactly-once accounting + sampled byte parity vs solo "
                "twins; requeue_p99_s = replica-death-to-first-"
                "requeued-job p99 from router failover events",
    }


def _router_chaos() -> None:
    """Standalone mode: run the router chaos soak and (with
    G2VEC_BENCH_ROUTER_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _router_chaos_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_ROUTER_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, ROUTER_CHAOS_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_router_chaos"}, f,
                      indent=1)
        note(f"wrote {ROUTER_CHAOS_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _partition_chaos_line(note) -> dict:
    """Partition drill: tools/chaos_soak.py --partition as a
    subprocess. Acceptance = exactly-once under false-dead fencing,
    zombie-leader epoch rejection, the standby takeover chain, and
    degraded-mode client drills in every routerless gap."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": str(PARTITION_JOBS),
           "G2V_CHAOS_SEED": str(PARTITION_SEED),
           "G2V_CHAOS_TAKEOVERS": str(PARTITION_TAKEOVERS),
           "G2V_CHAOS_BUDGET": str(PARTITION_BUDGET),
           "G2V_CHAOS_STREAM_FRAC": "0",
           "G2V_CHAOS_VERIFY": "2"}
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_soak.py"),
         "--partition"],
        capture_output=True, text=True, env=env,
        timeout=PARTITION_BUDGET + 180)
    for ln in (proc.stderr or "").splitlines():
        if ln.startswith("# "):
            note(f"partition {ln[2:]}")
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        raise RuntimeError(
            f"partition drill emitted no summary "
            f"(rc={proc.returncode}): "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    accepted = summary.get("accepted", 0) or 1
    accounted = accepted - len(summary.get("lost", ()))
    return {
        "metric": "partition_accounted_fraction",
        "value": round(accounted / accepted, 4), "unit": "fraction",
        "ok": bool(summary.get("ok")) and proc.returncode == 0,
        "jobs": summary.get("jobs"),
        "replicas": summary.get("replicas"),
        "lease_ttl_s": summary.get("lease_ttl_s"),
        "accepted": accepted,
        "terminal_by_status": summary.get("terminal_by_status"),
        "lost": len(summary.get("lost", ())),
        "duplicated": len(summary.get("duplicated", ())),
        "fence_epoch": summary.get("fence_epoch"),
        "quarantine_to_park_s": summary.get("quarantine_to_park_s"),
        "quarantine_parked": summary.get("quarantine_parked"),
        "fenced_replica_violations":
            summary.get("fenced_replica_violations"),
        "fenced_stays_out": summary.get("fenced_stays_out"),
        "stale_probe_rejects": summary.get("stale_probe_rejects"),
        "stale_probe_targets": summary.get("stale_probe_targets"),
        "zombie_rejects": summary.get("zombie_rejects"),
        "takeovers": summary.get("takeovers"),
        "takeover_p50_s": summary.get("takeover_p50_s"),
        "takeover_p99_s": summary.get("takeover_p99_s"),
        "degraded_submits": summary.get("degraded_submits"),
        "degraded_status_ok": summary.get("degraded_status_ok"),
        "failovers": summary.get("failovers"),
        "requeue_p50_s": summary.get("requeue_p50_s"),
        "requeue_p99_s": summary.get("requeue_p99_s"),
        "byte_checked": summary.get("byte_checked"),
        "byte_identical": summary.get("byte_identical"),
        "seed": summary.get("seed"),
        "wall_s": round(time.time() - t0, 1),
        "note": "relay-blackhole control-plane drill (false-dead fence "
                "+ replica self-quarantine, SIGSTOP zombie leader with "
                "stale_epoch rejection matrix, SIGKILL takeover chain "
                "with degraded-mode client drills in the gaps); "
                "takeover_p50/p99_s = fault-to-new-router-answering as "
                "a client measures it",
    }


def _partition_chaos() -> None:
    """Standalone mode: run the partition drill and (with
    G2VEC_BENCH_PARTITION_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _partition_chaos_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_PARTITION_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, PARTITION_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_partition_chaos"}, f,
                      indent=1)
        note(f"wrote {PARTITION_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _autoscale_arm(note, tag, extra_argv) -> dict:
    """One arm of the autoscale A/B: tools/chaos_soak.py --autoscale
    under the shared seeded schedule, static or elastic per
    extra_argv."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": str(AUTOSCALE_JOBS),
           "G2V_CHAOS_SEED": str(AUTOSCALE_SEED),
           "G2V_CHAOS_BUDGET": str(AUTOSCALE_BUDGET),
           "G2V_CHAOS_STREAM_FRAC": "0",
           "G2V_CHAOS_VERIFY": "2"}
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_soak.py"),
         "--autoscale", "--replicas", "1"] + extra_argv,
        capture_output=True, text=True, env=env,
        timeout=AUTOSCALE_BUDGET + 180)
    for ln in (proc.stderr or "").splitlines():
        if ln.startswith("# "):
            note(f"autoscale[{tag}] {ln[2:]}")
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        raise RuntimeError(
            f"autoscale soak ({tag}) emitted no summary "
            f"(rc={proc.returncode}): "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    summary["_rc"] = proc.returncode
    return summary


def _autoscale_arm_digest(summary) -> dict:
    """The per-arm fields the A/B verdict and the artifact reader
    care about."""
    return {
        "ok": bool(summary.get("ok")) and summary.get("_rc") == 0,
        "deadline_deaths": summary.get("deadline_deaths"),
        "attainment": summary.get("attainment"),
        "attainment_overall": summary.get("attainment_overall"),
        "goodput_done_per_min": summary.get("goodput_done_per_min"),
        "terminal_by_status": summary.get("terminal_by_status"),
        "accepted": summary.get("accepted"),
        "gave_up": summary.get("gave_up"),
        "lost": len(summary.get("lost", ())),
        "duplicated": len(summary.get("duplicated", ())),
        "replica_kills": summary.get("replica_kills"),
        "failovers": summary.get("failovers"),
        "shed_events": summary.get("shed_events"),
        "quota_events": summary.get("quota_events"),
        "shed_fraction": summary.get("shed_fraction"),
        "scale_ups": summary.get("scale_ups"),
        "scale_downs": summary.get("scale_downs"),
        "scale_up_reaction_p50_s": summary.get("scale_up_reaction_p50_s"),
        "scale_up_reaction_max_s": summary.get("scale_up_reaction_max_s"),
        "max_active_seen": summary.get("max_active_seen"),
        "warm_pool_events": summary.get("warm_pool_events"),
        "wall_s": summary.get("wall_s"),
    }


def _autoscale_ab_line(note) -> dict:
    """Elastic autoscaling A/B: identical seeded diurnal+burst tenant
    schedule (replica SIGKILLed mid-spike in both arms) against a
    static 1-replica fleet and the elastic fleet (max 2, one
    pre-warmed spare, deadline shed + tenant quotas)."""
    t0 = time.time()
    static = _autoscale_arm(note, "static", [])
    elastic = _autoscale_arm(
        note, "elastic",
        ["--max-replicas", "2", "--warm-spares", "1", "--shed",
         "--tenant-quotas", AUTOSCALE_QUOTAS])
    st, el = _autoscale_arm_digest(static), _autoscale_arm_digest(elastic)
    st_deaths = st["deadline_deaths"]
    el_deaths = el["deadline_deaths"]
    ok = (st["ok"] and el["ok"]
          and st_deaths is not None and st_deaths >= 4
          and el_deaths is not None and el_deaths <= 1
          and st["lost"] == 0 and el["lost"] == 0
          and st["duplicated"] == 0 and el["duplicated"] == 0
          and (el["attainment_overall"] or 0.0)
          >= (st["attainment_overall"] or 1.0))
    return {
        "metric": "autoscale_deadline_deaths_averted",
        "value": (st_deaths - el_deaths
                  if None not in (st_deaths, el_deaths) else None),
        "unit": "jobs", "ok": ok,
        "jobs": AUTOSCALE_JOBS, "seed": AUTOSCALE_SEED,
        "tenant_quotas": AUTOSCALE_QUOTAS,
        "static": st, "elastic": el,
        "wall_s": round(time.time() - t0, 1),
        "note": "same seeded diurnal+burst schedule (gold/silver/bulk "
                "tenants, replica SIGKILL mid-spike) twice: static "
                "1-replica fleet vs elastic (max 2, one pre-warmed "
                "spare, deadline shed + tenant quotas); acceptance = "
                "static reproduces >=4/50 deadline deaths, elastic "
                "<=1 with attainment at least as good, both arms "
                "0 lost / 0 duplicated across every scale and kill "
                "event",
    }


def _autoscale_ab() -> None:
    """Standalone mode: run the autoscale A/B and (with
    G2VEC_BENCH_AUTOSCALE_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _autoscale_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_AUTOSCALE_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, AUTOSCALE_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_autoscale_ab"}, f,
                      indent=1)
        note(f"wrote {AUTOSCALE_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _query_latency_line(note) -> dict:
    """Interactive query plane under realistic duress — the PR 15 proof.

    One router fronting QUERY_REPLICAS daemon replicas. Warmup jobs
    (distinct trainer shapes, so the join-key ring spreads them) publish
    one bundle each; then a seeded Poisson stream of neighbors /
    topk_biomarkers / meta queries runs for QUERY_DURATION seconds WHILE
    background training jobs occupy the fleet, and one bundle-owning
    replica is SIGKILLed mid-window — queries against its bundles must
    keep answering from the router's shared-disk read path. Cold
    latency (first touch: mmap + manifest sha256) is measured per
    bundle before the storm; the exactness spot check recomputes one
    neighbors answer from the bundle bytes with ops/knn in THIS process
    and demands float-exact agreement.

    No jax in this process: the fleet children import it; the local
    recompute is numpy-only by the query plane's design.
    """
    import random
    import shutil
    import signal
    import tempfile
    import threading

    import numpy as np

    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.ops import knn
    from g2vec_tpu.serve import client as sclient
    from g2vec_tpu.serve import protocol

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    rng = random.Random(QUERY_SEED)

    def _pct(xs, q):
        s = sorted(xs)
        return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)

    wd = tempfile.mkdtemp(prefix="g2v-query-")
    fleet = os.path.join(wd, "fleet")
    router_log = os.path.join(wd, "router.log")
    proc = None
    try:
        spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                             n_background=24, n_expr_only=4, n_net_only=4,
                             module_chords=2, background_edges=40, seed=7)
        paths = write_synthetic_tsv(spec, wd)

        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--replicas", str(QUERY_REPLICAS),
                "--listen", "127.0.0.1:0", "--state-dir", fleet,
                "--platform", "cpu",
                "--cache-dir", os.path.join(wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--probe-interval", "0.4", "--probe-deadline", "3.0",
                "--metrics-jsonl", os.path.join(wd, "router-metrics.jsonl")]
        log = open(router_log, "a")
        proc = subprocess.Popen(argv, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        log.close()
        addr_file = os.path.join(fleet, "router_addr")
        deadline = time.time() + 600
        addr = None
        while time.time() < deadline:
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    addr = f.read().strip()
                if addr:
                    break
            if proc.poll() is not None:
                raise RuntimeError(f"router died during boot (rc="
                                   f"{proc.returncode}; log: {router_log})")
            time.sleep(0.2)
        if not addr:
            raise RuntimeError(f"router never bound (log: {router_log})")
        note(f"router up at {addr} ({QUERY_REPLICAS} replicas)")

        def job(name, hidden, epochs=30):
            return {"expression_file": paths["expression"],
                    "clinical_file": paths["clinical"],
                    "network_file": paths["network"],
                    "result_name": os.path.join(wd, "out", name),
                    "lenPath": 8, "numRepetition": 2,
                    "sizeHiddenlayer": hidden, "epoch": epochs,
                    "learningRate": 0.05, "numBiomarker": 5,
                    "compute_dtype": "float32",
                    "walker_backend": "device"}

        os.makedirs(os.path.join(wd, "out"), exist_ok=True)
        # Warmup: distinct trainer shapes so the join-key ring spreads
        # the bundles over the fleet instead of batching them together.
        hiddens = [16, 24, 32, 20, 28, 36, 40, 48, 12, 44][:QUERY_JOBS]
        job_ids = [None] * len(hiddens)

        def run_warm(i):
            evs = list(sclient.submit_job(
                addr, job(f"w{i}", hiddens[i]), timeout=900.0))
            jid = next((e.get("job_id") for e in evs
                        if e.get("event") == "accepted"), None)
            if any(e.get("event") == "job_done" for e in evs):
                job_ids[i] = jid

        t_warm = time.time()
        threads = [threading.Thread(target=run_warm, args=(i,))
                   for i in range(len(hiddens))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900)
        bundles = {}          # job_id -> (replica, bundle_dir, genes)
        for jid in job_ids:
            if jid is None:
                continue
            for i in range(QUERY_REPLICAS):
                d = os.path.join(fleet, f"r{i}", "state", "inventory",
                                 jid, "v")
                if os.path.isdir(d):
                    with open(os.path.join(d, "genes.txt")) as f:
                        genes = [ln.rstrip("\n") for ln in f]
                    bundles[jid] = (f"r{i}", d, genes)
        note(f"warmup: {len(bundles)}/{len(hiddens)} bundles published "
             f"in {time.time() - t_warm:.1f}s on "
             f"{sorted({v[0] for v in bundles.values()})}")
        if not bundles:
            raise RuntimeError("no bundles published — nothing to query")

        # Background training load for the whole query window.
        def run_bg(i):
            try:
                for _ in sclient.submit_job(
                        addr, job(f"bg{i}", 16 + 4 * i, epochs=300),
                        timeout=900.0):
                    pass
            except (OSError, sclient.ServeConnectionLost,
                    sclient.ServeTimeout):
                pass
        bg = [threading.Thread(target=run_bg, args=(i,), daemon=True)
              for i in range(QUERY_BG_JOBS)]
        for t in bg:
            t.start()

        def one_query(**kw):
            t0 = time.time()
            resp = sclient.query(addr, timeout=30.0, **kw)
            return (time.time() - t0) * 1e3, resp

        jids = sorted(bundles)
        cold = []
        for jid in jids:
            ms, resp = one_query(q="neighbors", job_id=jid,
                                 gene=bundles[jid][2][0], k=10)
            if resp.get("event") != "query_result":
                raise RuntimeError(f"cold query failed: {resp}")
            cold.append(ms)
        note(f"cold first-touch: p50 {_pct(cold, 0.5)}ms "
             f"max {max(cold):.1f}ms over {len(cold)} bundles")

        # The seeded Poisson storm, with a mid-window replica SIGKILL.
        victim = bundles[jids[0]][0]
        st = sclient.status(addr, timeout=10.0)
        victim_pid = (st.get("replicas") or {}).get(victim, {}).get("pid")
        kill_at = time.time() + QUERY_DURATION * 0.4
        killed = False
        warm = {"neighbors": [], "topk_biomarkers": [], "meta": []}
        router_local = []
        errors = []
        end = time.time() + QUERY_DURATION
        while time.time() < end:
            if not killed and time.time() >= kill_at and victim_pid:
                note(f"SIGKILL replica {victim} (pid {victim_pid}) "
                     f"mid-window")
                try:
                    os.kill(victim_pid, signal.SIGKILL)
                except OSError:
                    pass
                killed = True
            jid = rng.choice(jids)
            genes = bundles[jid][2]
            op = rng.choice(("neighbors", "neighbors", "topk_biomarkers",
                             "meta"))
            kw = {"q": op, "job_id": jid}
            if op == "neighbors":
                kw.update(gene=rng.choice(genes), k=rng.randint(5, 50))
            elif op == "topk_biomarkers":
                kw.update(k=rng.randint(5, 20))
            try:
                ms, resp = one_query(**kw)
            except (OSError, protocol.ProtocolError) as e:
                errors.append(f"{type(e).__name__}: {e}"[:120])
                continue
            if resp.get("event") != "query_result":
                errors.append(str(resp)[:120])
                continue
            warm[op].append(ms)
            if resp.get("served_by") == "router":
                router_local.append(ms)
            time.sleep(rng.expovariate(QUERY_RATE))

        # Exactness spot check: recompute one answer from the bundle
        # bytes in THIS process; the served result must be float-exact.
        jid = jids[-1]
        _, bdir, genes = bundles[jid]
        emb = np.load(os.path.join(bdir, "embeddings.npy"))
        norms = np.load(os.path.join(bdir, "norms.npy"))
        gi = rng.randrange(len(genes))
        _, resp = one_query(q="neighbors", job_id=jid, gene=genes[gi],
                            k=7)
        idx, sims = knn.cosine_topk(emb, norms, emb[gi], 7, exclude=gi)
        exact = (resp.get("neighbors") == [genes[i] for i in idx]
                 and resp.get("sims") == [float(s) for s in sims])
        note(f"exactness spot check: {'ok' if exact else 'MISMATCH'}")

        n_warm = sum(len(v) for v in warm.values())
        nb_p99 = _pct(warm["neighbors"], 0.99) if warm["neighbors"] else None
        tk_p99 = (_pct(warm["topk_biomarkers"], 0.99)
                  if warm["topk_biomarkers"] else None)
        ok = (exact and not errors and killed and bool(router_local)
              and nb_p99 is not None and nb_p99 < QUERY_P99_MS
              and tk_p99 is not None and tk_p99 < QUERY_P99_MS)
        return {
            "metric": "query_warm_neighbors_p99_ms", "value": nb_p99,
            "unit": "ms", "ok": ok,
            "replicas": QUERY_REPLICAS, "bundles": len(bundles),
            "queries_warm": n_warm, "query_errors": len(errors),
            "errors_sample": errors[:5],
            "cold_p50_ms": _pct(cold, 0.5),
            "cold_p99_ms": _pct(cold, 0.99),
            "warm_neighbors_p50_ms": _pct(warm["neighbors"], 0.5)
            if warm["neighbors"] else None,
            "warm_neighbors_p99_ms": nb_p99,
            "warm_topk_p50_ms": _pct(warm["topk_biomarkers"], 0.5)
            if warm["topk_biomarkers"] else None,
            "warm_topk_p99_ms": tk_p99,
            "warm_meta_p50_ms": _pct(warm["meta"], 0.5)
            if warm["meta"] else None,
            "router_local_queries": len(router_local),
            "router_local_p99_ms": _pct(router_local, 0.99)
            if router_local else None,
            "replica_killed": victim if killed else None,
            "bg_training_jobs": QUERY_BG_JOBS,
            "exactness_ok": exact, "p99_budget_ms": QUERY_P99_MS,
            "seed": QUERY_SEED, "rate_hz": QUERY_RATE,
            "duration_s": QUERY_DURATION,
            "note": "seeded Poisson neighbors/topk_biomarkers/meta load "
                    "vs a replicated fleet under concurrent training; "
                    "one bundle-owning replica SIGKILLed mid-window "
                    "(router_local_* = queries answered from the "
                    "router's shared-disk failover read path); cold = "
                    "first touch paying mmap + manifest sha256",
        }
    finally:
        if proc is not None and proc.poll() is None:
            try:
                from g2vec_tpu.serve import client as sclient2

                with open(os.path.join(fleet, "router_addr")) as f:
                    sclient2.shutdown(f.read().strip(), timeout=15.0)
            except Exception:
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(wd, ignore_errors=True)


def _query_latency() -> None:
    """Standalone mode: run the query-plane latency proof and (with
    G2VEC_BENCH_QUERY_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _query_latency_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_QUERY_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, QUERY_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_query_latency"}, f,
                      indent=1)
        note(f"wrote {QUERY_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _ann_ab_line(note) -> dict:
    """Approximate-NN query plane A/B — the PR 18 proof.

    Three arms. (a) QPS frontier: for each bundle size in ANN_SIZES,
    build the IVF index (stage-5-style clustered embeddings) and race
    per-query latency of ops/ann.ivf_topk at the default nprobe against
    ops/knn.cosine_topk full scans; the largest size must clear
    ANN_SPEEDUP_MIN x with approx p99 under ANN_P99_MS and recall@10 at
    the default nprobe >= 0.95 (the pinned contract, measured not
    assumed); each size also A/Bs the posting-major candidate storage
    (one contiguous slab read per probed list) against the row-gather
    path — same queries, bitwise-equal answers required. (b) Recall
    curve: recall@10 / candidate fraction / p50
    over the ANN_NPROBES ladder at the largest size, ending at
    nprobe=nlist where the result must be BITWISE equal to exact.
    (c) Federated: plant indexed bundles across a real router fleet's
    shared state dirs, boot it, and run a seeded gene_rank /
    bundle_overlap storm with one bundle-owning replica SIGKILLed
    mid-window — its bundles must keep answering from the router's
    disk read path (replica_down=True partials) with zero errors.

    No jax in this process: ops/ann + ops/knn are numpy by design and
    the fleet children own their own interpreters.
    """
    import random
    import shutil
    import signal
    import tempfile

    import numpy as np

    from g2vec_tpu.io.writers import write_inventory_bundle
    from g2vec_tpu.ops import ann, knn
    from g2vec_tpu.serve import client as sclient
    from g2vec_tpu.serve import protocol

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    rng = np.random.default_rng(ANN_SEED)
    k = 10

    def _pct(xs, q):
        s = sorted(xs)
        return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)

    def make_data(g, h):
        # Clustered like real stage-4 output: k-means structure is what
        # an IVF index exploits, uniform noise would be adversarial.
        ncl = max(32, int(round(g ** 0.5)))
        centers = rng.standard_normal((ncl, h)).astype(np.float32)
        emb = centers[rng.integers(0, ncl, size=g)]
        emb += 0.3 * rng.standard_normal((g, h)).astype(np.float32)
        return np.ascontiguousarray(emb, dtype=np.float32)

    # ---- (a) QPS x bundle-size frontier -------------------------------
    sizes = sorted(int(s) for s in ANN_SIZES.split(",") if s.strip())
    frontier = []
    emb = norms = index = None
    for g in sizes:
        emb = make_data(g, ANN_HIDDEN)
        norms = knn.row_norms(emb)
        # Auto past the row floor; forced for env-shrunk smoke sizes.
        nlist = ann.resolve_nlist(g, 0) or ann.resolve_nlist(g, 64)
        t0 = time.perf_counter()
        cents, posts, offs = ann.build_ivf(emb, nlist)
        build_s = time.perf_counter() - t0
        index = ann.IVFIndex(cents, posts, offs, g, ANN_HIDDEN)
        # Posting-major twin: same lists, but candidate vectors stored
        # contiguously in posting order so each probed list is one slab.
        t0 = time.perf_counter()
        pm_index = ann.IVFIndex(cents, posts, offs, g, ANN_HIDDEN,
                                pvecs=np.ascontiguousarray(emb[posts]))
        pm_build_s = time.perf_counter() - t0
        qidx = rng.integers(0, g, size=ANN_QUERIES)
        for qi in qidx[:8]:     # warm all three paths (allocator, BLAS)
            knn.cosine_topk(emb, norms, emb[qi], k, exclude=int(qi))
            ann.ivf_topk(emb, norms, index, emb[qi], k,
                         nprobe=ann.DEFAULT_NPROBE, exclude=int(qi))
            ann.ivf_topk(emb, norms, pm_index, emb[qi], k,
                         nprobe=ann.DEFAULT_NPROBE, exclude=int(qi),
                         posting_major=True)
        ex_ms, exact_ids = [], []
        for qi in qidx:
            t1 = time.perf_counter()
            idx, _ = knn.cosine_topk(emb, norms, emb[qi], k,
                                     exclude=int(qi))
            ex_ms.append((time.perf_counter() - t1) * 1e3)
            exact_ids.append(set(int(i) for i in idx))
        ap_ms, hits, cands, gather_out = [], 0, 0, []
        for qi, ex in zip(qidx, exact_ids):
            t1 = time.perf_counter()
            idx, sims, nc = ann.ivf_topk(emb, norms, index, emb[qi], k,
                                         nprobe=ann.DEFAULT_NPROBE,
                                         exclude=int(qi))
            ap_ms.append((time.perf_counter() - t1) * 1e3)
            hits += len(ex & set(int(i) for i in idx))
            cands += nc
            gather_out.append((idx, sims))
        # Storage A/B: same queries through the posting-major slab
        # layout — must be bitwise-equal to the gather path at the
        # same nprobe (pvecs rows are byte-equal copies).
        pm_ms, pm_bitwise = [], True
        for qi, (gi, gs) in zip(qidx, gather_out):
            t1 = time.perf_counter()
            idx, sims, _ = ann.ivf_topk(emb, norms, pm_index, emb[qi],
                                        k, nprobe=ann.DEFAULT_NPROBE,
                                        exclude=int(qi),
                                        posting_major=True)
            pm_ms.append((time.perf_counter() - t1) * 1e3)
            pm_bitwise &= (np.array_equal(gi, idx)
                           and np.array_equal(gs, sims))
        # Full-probe spot check: nprobe=nlist must be bitwise exact.
        bitwise = True
        for qi in qidx[:10]:
            ei, es = knn.cosine_topk(emb, norms, emb[qi], k,
                                     exclude=int(qi))
            ai, as_, _ = ann.ivf_topk(emb, norms, index, emb[qi], k,
                                      nprobe=nlist, exclude=int(qi))
            bitwise &= (np.array_equal(ei, ai)
                        and np.array_equal(es, as_))
        row = {
            "genes": g, "hidden": ANN_HIDDEN, "nlist": nlist,
            "build_s": round(build_s, 3),
            "exact_qps": round(len(ex_ms) / (sum(ex_ms) / 1e3), 1),
            "approx_qps": round(len(ap_ms) / (sum(ap_ms) / 1e3), 1),
            "exact_p50_ms": _pct(ex_ms, 0.5),
            "exact_p99_ms": _pct(ex_ms, 0.99),
            "approx_p50_ms": _pct(ap_ms, 0.5),
            "approx_p99_ms": _pct(ap_ms, 0.99),
            "recall_at_10": round(hits / (k * len(qidx)), 4),
            "cand_frac": round(cands / (len(qidx) * g), 4),
            "nprobe": ann.DEFAULT_NPROBE,
            "bitwise_full_probe_ok": bool(bitwise),
            "pm_build_s": round(pm_build_s, 3),
            "pm_qps": round(len(pm_ms) / (sum(pm_ms) / 1e3), 1),
            "pm_p50_ms": _pct(pm_ms, 0.5),
            "pm_p99_ms": _pct(pm_ms, 0.99),
            "pm_bitwise_vs_gather_ok": bool(pm_bitwise),
        }
        row["speedup_x"] = round(row["approx_qps"]
                                 / max(row["exact_qps"], 1e-9), 2)
        row["pm_vs_gather_x"] = round(row["pm_qps"]
                                      / max(row["approx_qps"], 1e-9), 2)
        frontier.append(row)
        note(f"frontier g={g}: exact {row['exact_qps']} qps, approx "
             f"{row['approx_qps']} qps ({row['speedup_x']}x), "
             f"posting-major {row['pm_qps']} qps "
             f"({row['pm_vs_gather_x']}x vs gather), recall@10 "
             f"{row['recall_at_10']}, cand {row['cand_frac']:.1%}, "
             f"build {row['build_s']}s")
    largest = frontier[-1]

    # ---- (b) recall@10 curve over nprobe (largest size) ---------------
    nprobes = sorted({int(s) for s in ANN_NPROBES.split(",") if s.strip()}
                     | {largest["nlist"]})
    g = largest["genes"]
    qidx = rng.integers(0, g, size=ANN_RECALL_QUERIES)
    exact_ids = [(qi, set(int(i) for i in knn.cosine_topk(
        emb, norms, emb[qi], k, exclude=int(qi))[0])) for qi in qidx]
    curve = []
    for npr in nprobes:
        ms, hits, cands = [], 0, 0
        for qi, ex in exact_ids:
            t1 = time.perf_counter()
            idx, _, nc = ann.ivf_topk(emb, norms, index, emb[qi], k,
                                      nprobe=npr, exclude=int(qi))
            ms.append((time.perf_counter() - t1) * 1e3)
            hits += len(ex & set(int(i) for i in idx))
            cands += nc
        curve.append({
            "nprobe": npr,
            "recall_at_10": round(hits / (k * len(exact_ids)), 4),
            "cand_frac": round(cands / (len(exact_ids) * g), 4),
            "p50_ms": _pct(ms, 0.5),
        })
        note(f"recall curve nprobe={npr}: recall@10 "
             f"{curve[-1]['recall_at_10']}, cand "
             f"{curve[-1]['cand_frac']:.1%}, p50 {curve[-1]['p50_ms']}ms")
    emb = norms = index = pm_index = None   # release before fleet boot

    # ---- (c) federated fquery storm with a mid-window SIGKILL ---------
    prng = random.Random(ANN_SEED)
    wd = tempfile.mkdtemp(prefix="g2v-ann-")
    fleet = os.path.join(wd, "fleet")
    router_log = os.path.join(wd, "router.log")
    proc = None
    try:
        genes = [f"G{i:05d}" for i in range(ANN_FED_GENES)]
        owners = {}
        for b in range(ANN_FED_BUNDLES):
            jid = f"i{b:012d}"
            rep = f"r{b % ANN_FED_REPLICAS}"
            dest = os.path.join(fleet, rep, "state", "inventory", jid,
                                "v0")
            bemb = make_data(ANN_FED_GENES, ANN_HIDDEN)
            scores = rng.standard_normal((2, ANN_FED_GENES)).astype(
                np.float32)
            write_inventory_bundle(dest, bemb, genes, scores,
                                   {"source": "bench"}, ann_nlist=64)
            owners[jid] = rep
        jids = sorted(owners)
        note(f"planted {len(jids)} indexed bundles "
             f"({ANN_FED_GENES} genes each) over "
             f"{ANN_FED_REPLICAS} replica state dirs")

        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--replicas", str(ANN_FED_REPLICAS),
                "--listen", "127.0.0.1:0", "--state-dir", fleet,
                "--platform", "cpu",
                "--probe-interval", "0.4", "--probe-deadline", "3.0",
                "--metrics-jsonl", os.path.join(wd, "metrics.jsonl")]
        log = open(router_log, "a")
        proc = subprocess.Popen(argv, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        log.close()
        addr_file = os.path.join(fleet, "router_addr")
        deadline = time.time() + 600
        addr = None
        while time.time() < deadline:
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    addr = f.read().strip()
                if addr:
                    break
            if proc.poll() is not None:
                raise RuntimeError(f"router died during boot (rc="
                                   f"{proc.returncode}; log: "
                                   f"{router_log})")
            time.sleep(0.2)
        if not addr:
            raise RuntimeError(f"router never bound (log: {router_log})")
        pids = {}
        while time.time() < deadline and len(pids) < ANN_FED_REPLICAS:
            st = sclient.status(addr, timeout=10.0)
            pids = {n: r.get("pid")
                    for n, r in (st.get("replicas") or {}).items()
                    if r.get("pid")}
            time.sleep(0.3)
        note(f"router up at {addr} ({len(pids)} replicas alive)")

        # Cold pass: first touch maps every bundle (mmap + manifest
        # sha256 + index map) on its home replica.
        cold = []
        for jid in jids:
            t1 = time.time()
            resp = sclient.query(addr, "neighbors", job_id=jid,
                                 gene=genes[0], k=10, timeout=60.0)
            if resp.get("event") != "query_result":
                raise RuntimeError(f"cold query failed: {resp}")
            if resp.get("recall_mode") != "approx":
                raise RuntimeError(
                    f"bundle {jid} not serving approx: {resp}")
            cold.append((time.time() - t1) * 1e3)
        note(f"cold first-touch: p50 {_pct(cold, 0.5)}ms over "
             f"{len(cold)} bundles (all recall_mode=approx)")

        victim = owners[jids[0]]
        victim_pid = pids.get(victim)
        kill_at = time.time() + ANN_FED_DURATION * 0.4
        killed = False
        lat = {"gene_rank": [], "bundle_overlap": []}
        errors = []
        down_partials = 0
        down_bundles = set()
        recall_modes = {}
        end = time.time() + ANN_FED_DURATION
        while time.time() < end:
            if not killed and time.time() >= kill_at and victim_pid:
                note(f"SIGKILL replica {victim} (pid {victim_pid}) "
                     f"mid-window")
                try:
                    os.kill(victim_pid, signal.SIGKILL)
                except OSError:
                    pass
                killed = True
            fq = prng.choice(("gene_rank", "gene_rank",
                              "bundle_overlap"))
            kw = {"gene": prng.choice(genes)}
            if fq == "gene_rank":
                kw["k"] = 50
            else:
                kw.update(k=20, job_id=prng.choice(jids))
            t1 = time.time()
            try:
                ev = sclient.fquery(addr, fq, timeout=30.0, **kw)
            except (OSError, protocol.ProtocolError) as e:
                errors.append(f"{type(e).__name__}: {e}"[:120])
                continue
            if ev.get("event") != "fquery_result":
                errors.append(str(ev)[:120])
                continue
            lat[fq].append((time.time() - t1) * 1e3)
            for p in ev.get("bundles") or []:
                rm = p.get("recall_mode")
                if rm:
                    recall_modes[rm] = recall_modes.get(rm, 0) + 1
                if p.get("replica_down"):
                    down_partials += 1
                    down_bundles.add(p.get("bundle"))
            time.sleep(prng.expovariate(ANN_FED_RATE))

        all_ms = lat["gene_rank"] + lat["bundle_overlap"]
        fed_p99 = _pct(all_ms, 0.99) if all_ms else None
        victim_bundles = {f"{j}/v0" for j, r in owners.items()
                          if r == victim}
        fed_ok = (killed and not errors and bool(all_ms)
                  and fed_p99 is not None and fed_p99 < ANN_FED_P99_MS
                  and victim_bundles <= down_bundles)
        fed = {
            "replicas": ANN_FED_REPLICAS, "bundles": len(jids),
            "genes_per_bundle": ANN_FED_GENES,
            "fqueries": len(all_ms), "fquery_errors": len(errors),
            "errors_sample": errors[:5],
            "cold_p50_ms": _pct(cold, 0.5),
            "gene_rank_p50_ms": _pct(lat["gene_rank"], 0.5)
            if lat["gene_rank"] else None,
            "gene_rank_p99_ms": _pct(lat["gene_rank"], 0.99)
            if lat["gene_rank"] else None,
            "overlap_p50_ms": _pct(lat["bundle_overlap"], 0.5)
            if lat["bundle_overlap"] else None,
            "overlap_p99_ms": _pct(lat["bundle_overlap"], 0.99)
            if lat["bundle_overlap"] else None,
            "p99_ms": fed_p99, "p99_budget_ms": ANN_FED_P99_MS,
            "replica_killed": victim if killed else None,
            "replica_down_partials": down_partials,
            "replica_down_bundles": sorted(down_bundles),
            "recall_modes": recall_modes,
            "ok": fed_ok,
        }
        note(f"federated: {len(all_ms)} fqueries, p99 {fed_p99}ms, "
             f"{down_partials} replica_down partials over "
             f"{sorted(down_bundles)}, recall modes {recall_modes}")
    finally:
        if proc is not None and proc.poll() is None:
            try:
                from g2vec_tpu.serve import client as sclient2

                with open(os.path.join(fleet, "router_addr")) as f:
                    sclient2.shutdown(f.read().strip(), timeout=15.0)
            except Exception:
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(wd, ignore_errors=True)

    ok = (largest["speedup_x"] >= ANN_SPEEDUP_MIN
          and largest["approx_p99_ms"] < ANN_P99_MS
          and largest["recall_at_10"] >= 0.95
          and all(r["bitwise_full_probe_ok"] for r in frontier)
          and all(r["pm_bitwise_vs_gather_ok"] for r in frontier)
          and curve[-1]["recall_at_10"] == 1.0
          and fed_ok)
    return {
        "metric": "ann_approx_speedup_x", "value": largest["speedup_x"],
        "unit": "x", "ok": ok,
        "speedup_min_x": ANN_SPEEDUP_MIN, "p99_budget_ms": ANN_P99_MS,
        "recall_contract": 0.95, "k": k, "seed": ANN_SEED,
        "frontier": frontier, "recall_curve": curve, "federated": fed,
        "note": "frontier: per-query approx (IVF, default nprobe) vs "
                "exact full-scan QPS on clustered embeddings, plus a "
                "posting-major storage A/B (contiguous slab reads vs "
                "row gathers, bitwise-equal answers); recall "
                "curve ends at nprobe=nlist (bitwise-equal to exact); "
                "federated: seeded gene_rank/bundle_overlap storm vs a "
                "live router fleet, one bundle-owning replica "
                "SIGKILLed mid-window, its bundles answered from the "
                "router's shared-disk read path (replica_down=True)",
    }


def _ann_ab() -> None:
    """Standalone mode: run the approximate-NN A/B and refresh the
    committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _ann_ab_line(note)
    print(json.dumps(line), flush=True)
    repo = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(repo, ANN_ARTIFACT), "w") as f:
        json.dump({"line": line, "code_key": _current_code_key(repo),
                   "written_by": "bench.py --_ann_ab"}, f, indent=1)
    note(f"wrote {ANN_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _update_ab_line(note) -> dict:
    """Incremental update plane A/B — the PR 19 proof.

    One synthetic cohort (a scaled-up cousin of the band-validated
    tests/test_update.py spec), four checkpoints. (a) Cold pipeline run ->
    published bundle -> bootstrap update, which re-walks every owner
    range once and records per-range walk artifacts + fingerprints.
    (b) No-op re-update: fingerprint-identical inputs must walk ZERO
    rows, hit the cache on every range, and republish array files that
    are byte-for-byte the prior generation's. (c) ~UPDATE_DELTA_FRAC
    edge delta: the delta re-walk + warm-start fine-tune must finish
    within UPDATE_WALL_FRAC x the wall of a cold retrain of the SAME
    updated inputs (both timed compile-warm, same process) while
    holding the PR 7 statistical band against it. (d) Torn-read probe:
    >= UPDATE_MIN_READS serve-path queries spanning UPDATE_FLIPS
    generation flips — every answer must be a complete pre-flip or
    post-flip result for its gene, never a mix.
    """
    import dataclasses
    import shutil
    import tempfile
    import threading

    import numpy as np

    from g2vec_tpu import pipeline
    from g2vec_tpu.cache import resolve_cache_tiers
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.incremental import (BAND_DACC, BAND_OVERLAP,
                                       run_update, within_band)
    from g2vec_tpu.io.writers import read_generation, write_inventory_bundle
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    ARRAYS = ("embeddings.npy", "norms.npy", "scores.npy", "genes.txt")

    def _array_bytes(gen_dir):
        out = {}
        for fn in ARRAYS:
            with open(os.path.join(gen_dir, fn), "rb") as f:
                out[fn] = f.read()
        return out

    wd = tempfile.mkdtemp(prefix="g2v-upd-")
    try:
        spec = SyntheticSpec(n_good=UPDATE_GOOD, n_poor=UPDATE_POOR,
                             module_size=UPDATE_MODULE,
                             shared_module_size=UPDATE_SMOD,
                             n_background=UPDATE_BG,
                             n_expr_only=4, n_net_only=4,
                             module_chords=2,
                             background_edges=UPDATE_BG_EDGES,
                             seed=UPDATE_SEED)
        syn = os.path.join(wd, "syn")
        os.makedirs(syn, exist_ok=True)
        tsv = write_synthetic_tsv(spec, syn)
        os.makedirs(os.path.join(wd, "out"), exist_ok=True)
        cfg = G2VecConfig(
            expression_file=tsv["expression"],
            clinical_file=tsv["clinical"],
            network_file=tsv["network"],
            result_name=os.path.join(wd, "out", "cold"),
            lenPath=UPDATE_LENPATH, numRepetition=UPDATE_REPS,
            sizeHiddenlayer=16, epoch=UPDATE_EPOCH, learningRate=0.05,
            numBiomarker=UPDATE_NBIO, compute_dtype="float32",
            walker_backend="device",
            cache_dir=os.path.join(wd, "cache"))

        # ---- (a) cold run -> publish -> bootstrap update --------------
        t0 = time.perf_counter()
        cold = pipeline.run(cfg, console=lambda s: None)
        cold_first_wall = time.perf_counter() - t0
        note(f"cold run (compile-inclusive): {cold_first_wall:.1f}s, "
             f"acc {cold.acc_val:.3f}")
        bundle = os.path.join(wd, "bundle")
        write_inventory_bundle(bundle, cold.embeddings, list(cold.genes),
                               cold.biomarker_scores, {"source": "cold"},
                               ann_nlist=4, seed_centroids=cold.km_centers)
        _, wc = resolve_cache_tiers(cfg.cache_dir, None, True)
        up1 = run_update(cfg, bundle, walk_cache=wc)
        write_inventory_bundle(
            bundle, up1.embeddings, up1.genes, up1.biomarker_scores,
            {"source": "update"}, ann_nlist=4,
            seed_centroids=up1.km_centers,
            extra_files={"delta_fingerprints.json": up1.fingerprints})
        boot = {k: up1.stats[k] for k in
                ("mode", "walked_rows", "ranges_rewalked", "ranges_total",
                 "n_genes", "wall_s")}
        boot["ok"] = (boot["mode"] == "bootstrap"
                      and boot["ranges_rewalked"] == boot["ranges_total"])
        note(f"bootstrap: {boot['ranges_total']} ranges, "
             f"{boot['walked_rows']} rows, {boot['wall_s']}s")

        # ---- (b) no-op re-update: zero walks, byte-identical arrays ---
        up2 = run_update(cfg, bundle, walk_cache=wc)
        gen_prev = os.path.join(bundle, read_generation(bundle))
        gen_noop = write_inventory_bundle(
            bundle, up2.embeddings, up2.genes, up2.biomarker_scores,
            {"source": "update"}, ann_nlist=4,
            extra_files={"delta_fingerprints.json": up2.fingerprints})
        byte_identical = _array_bytes(gen_prev) == _array_bytes(gen_noop)
        noop = {k: up2.stats[k] for k in
                ("mode", "walked_rows", "ranges_rewalked", "cache_hits",
                 "ranges_total", "wall_s")}
        noop["byte_identical_arrays"] = bool(byte_identical)
        noop["ok"] = (noop["mode"] == "noop" and noop["walked_rows"] == 0
                      and noop["cache_hits"] == noop["ranges_total"]
                      and byte_identical)
        note(f"noop: walked {noop['walked_rows']} rows, byte-identical "
             f"arrays {byte_identical}, {noop['wall_s']}s")

        # ---- (c) ~1% edge delta: delta wall vs cold-retrain wall ------
        with open(tsv["network"]) as f:
            lines = f.read().splitlines()
        header, rows = lines[0], [r for r in lines[1:] if r.strip()]
        have = set()
        for r in rows:
            a, b = r.split("\t")[:2]
            have.add((a, b))
            have.add((b, a))
        gmod = sorted({g for pair in have for g in pair
                       if g.startswith("GMOD")})
        m = max(1, int(round(UPDATE_DELTA_FRAC * len(rows))))
        new_pairs = []
        for a in gmod:
            for b in gmod:
                if a < b and (a, b) not in have:
                    new_pairs.append((a, b))
                    have.add((a, b))
                    have.add((b, a))
                if len(new_pairs) >= m:
                    break
            if len(new_pairs) >= m:
                break
        net2 = os.path.join(wd, "net_delta.txt")
        with open(net2, "w") as f:
            f.write("\n".join([header] + rows
                              + [f"{a}\t{b}" for a, b in new_pairs])
                    + "\n")
        cfg_d = dataclasses.replace(
            cfg, network_file=net2,
            result_name=os.path.join(wd, "out", "cold2"))
        t0 = time.perf_counter()
        cold2 = pipeline.run(cfg_d, console=lambda s: None)
        cold_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        upd = run_update(cfg_d, bundle, walk_cache=wc)
        delta_wall = time.perf_counter() - t0
        ratio = delta_wall / max(cold_wall, 1e-9)
        band_ok, band_detail = within_band(
            upd.acc_val, cold2.acc_val, upd.biomarkers, cold2.biomarkers)
        delta = {k: upd.stats[k] for k in
                 ("mode", "walked_rows", "ranges_rewalked",
                  "ranges_total", "cache_hits", "epochs", "stop_epoch")}
        delta.update({
            "edges_added": len(new_pairs), "edges_base": len(rows),
            "delta_frac": round(len(new_pairs) / len(rows), 4),
            "cold_first_wall_s": round(cold_first_wall, 3),
            "cold_wall_s": round(cold_wall, 3),
            "delta_wall_s": round(delta_wall, 3),
            "wall_frac": round(ratio, 3),
            "wall_budget": UPDATE_WALL_FRAC,
            "subset_rewalk": bool(0 < delta["ranges_rewalked"]
                                  < delta["ranges_total"]),
        })
        delta["ok"] = (delta["mode"] == "delta" and delta["subset_rewalk"]
                       and ratio <= UPDATE_WALL_FRAC)
        band = {"dacc": band_detail["dacc"],
                "overlap": band_detail["overlap"],
                "dacc_budget": BAND_DACC, "overlap_floor": BAND_OVERLAP,
                "delta_acc": round(float(upd.acc_val), 4),
                "cold_acc": round(float(cold2.acc_val), 4),
                "ok": bool(band_ok)}
        note(f"delta: +{len(new_pairs)} edges "
             f"({delta['delta_frac']:.1%}), rewalked "
             f"{delta['ranges_rewalked']}/{delta['ranges_total']} "
             f"ranges, wall {delta['delta_wall_s']}s vs cold "
             f"{delta['cold_wall_s']}s ({delta['wall_frac']}x), band "
             f"dacc {band['dacc']} overlap {band['overlap']}")

        # ---- (d) torn-read probe across generation flips --------------
        sd = ServeDaemon(ServeOptions(
            socket_path=os.path.join(wd, "serve.sock"),
            state_dir=os.path.join(wd, "state")), console=lambda s: None)
        try:
            rng = np.random.default_rng(UPDATE_SEED)
            g, h = 64, 16
            genes = [f"G{i:05d}" for i in range(g)]
            emb_a = rng.standard_normal((g, h)).astype(np.float32)
            emb_b = np.ascontiguousarray(emb_a[::-1])
            probes = genes[:6]

            def plant(jid, emb):
                root = os.path.join(sd.opts.state_dir, "inventory",
                                    jid, "v0")
                write_inventory_bundle(root, emb, genes, None, {})
                return root

            plant("i" + "a" * 12, emb_a)
            plant("i" + "b" * 12, emb_b)
            live = plant("i" + "e" * 12, emb_a)

            def answer(jid, gene):
                r = sd.handle_query({"q": "neighbors", "job_id": jid,
                                     "variant": "v0", "gene": gene,
                                     "k": 5, "mode": "exact"})
                if r.get("event") != "query_result":
                    raise RuntimeError(str(r)[:200])
                return (tuple(r["neighbors"]), tuple(r["sims"]))

            expect = {gene: {answer("i" + "a" * 12, gene),
                             answer("i" + "b" * 12, gene)}
                      for gene in probes}
            stop = threading.Event()

            def writer():
                for i in range(UPDATE_FLIPS):
                    emb = emb_b if i % 2 == 0 else emb_a
                    write_inventory_bundle(live, emb, genes, None, {})
                    key = "i" + "e" * 12 + "/v0"
                    sd.catalog.invalidate(key)
                    sd.qcache.invalidate_bundle(key)
                    sd._inv_known = {}
                    time.sleep(0.05)
                stop.set()

            t = threading.Thread(target=writer)
            t.start()
            reads, torn = 0, 0
            while not stop.is_set() or reads < 2 * UPDATE_MIN_READS:
                gene = probes[reads % len(probes)]
                if answer("i" + "e" * 12, gene) not in expect[gene]:
                    torn += 1
                reads += 1
                if reads > 20000:
                    break
                # In-process reads are ~50k/s; pace them so the read
                # window actually spans every flip.
                time.sleep(0.0005)
            t.join()
        finally:
            sd.close()
        torn_probe = {"reads": reads, "flips": UPDATE_FLIPS,
                      "torn": torn, "min_reads": UPDATE_MIN_READS,
                      "ok": bool(reads >= UPDATE_MIN_READS
                                 and torn == 0)}
        note(f"torn probe: {reads} reads across {UPDATE_FLIPS} flips, "
             f"{torn} torn")
    finally:
        shutil.rmtree(wd, ignore_errors=True)

    ok = (boot["ok"] and noop["ok"] and delta["ok"] and band["ok"]
          and torn_probe["ok"])
    return {
        "metric": "update_delta_wall_frac",
        "value": delta["wall_frac"], "unit": "x_cold_wall",
        "budget": UPDATE_WALL_FRAC, "ok": ok, "seed": UPDATE_SEED,
        "cohort": {"n_good": UPDATE_GOOD, "n_poor": UPDATE_POOR,
                   "module_size": UPDATE_MODULE,
                   "shared_module_size": UPDATE_SMOD,
                   "n_background": UPDATE_BG,
                   "background_edges": UPDATE_BG_EDGES,
                   "numBiomarker": UPDATE_NBIO,
                   "lenPath": UPDATE_LENPATH, "reps": UPDATE_REPS,
                   "epoch": UPDATE_EPOCH},
        "bootstrap": boot, "noop": noop, "delta": delta, "band": band,
        "torn_probe": torn_probe,
        "note": "delta wall vs cold-retrain wall, both compile-warm in "
                "one process; no-op republish must be byte-identical; "
                "band is the PR 7 contract (|dACC|, top-N biomarker "
                "overlap) vs a cold retrain of the updated inputs; "
                "torn probe hammers a live daemon across generation "
                "flips (complete-old or complete-new, never a mix)",
    }


def _update_ab() -> None:
    """Standalone mode: run the incremental-update A/B and refresh the
    committed artifact."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _update_ab_line(note)
    print(json.dumps(line), flush=True)
    repo = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(repo, UPDATE_ARTIFACT), "w") as f:
        json.dump({"line": line, "code_key": _current_code_key(repo),
                   "written_by": "bench.py --_update_ab"}, f, indent=1)
    note(f"wrote {UPDATE_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _device_walk_line(note) -> dict:
    """On-device walk sampling A/B — the PR 20 proof.

    (a) Sampler A/B: host C++ pool vs the device CSR sampler over the
    SAME shard plan, min-of-N timings, with the packed rows compared
    byte-for-byte on EVERY timed shard — a mismatch fails the bench, so
    a paths/s number can never be quoted for a walker that drifted off
    the bit-exact contract. Device compile time is reported separately
    from steady-state sampling (the jit cache amortizes it across
    shards of one (len_path, degree-bucket) shape).
    (b) Feed A/B: native-ring streaming vs the fused --device-feed arm
    at the same config — end-to-end wall, time-to-first-update (the
    instant the first shard is ready at the trainer), h2d_bytes_saved,
    and the zero-host-ring-puts invariant, with final embeddings
    byte-identical across arms.
    (c) Chip sweep: genes x paths/s cells that only mean anything with
    a real accelerator attached; off-chip they are emitted as explicit
    null lines so a watcher run on hardware is REQUIRED to fill them.
    """
    import numpy as np

    import jax

    from g2vec_tpu.ops import device_walker as dwk
    from g2vec_tpu.ops import host_walker as hwk
    from g2vec_tpu.train.stream import train_cbow_streaming

    platform = jax.devices()[0].platform
    on_chip = platform not in ("cpu",)
    G, E, L = DEVICE_WALK_GENES, DEVICE_WALK_EDGES, DEVICE_WALK_LEN
    wreps = DEVICE_WALK_WREPS

    r = np.random.default_rng(7)
    src = r.integers(0, G, size=E).astype(np.int32)
    dst = r.integers(0, G, size=E).astype(np.int32)
    w = r.random(E, dtype=np.float32)

    # -- (a) sampler A/B over one shard plan, bit identity in-run ------
    plan = hwk.plan_shards(G, wreps, 0, len_path=L)
    shards = list(range(min(plan.n_shards, DEVICE_WALK_SHARDS)))
    note(f"sampler A/B: G={G} E={E} L={L} reps={wreps} "
         f"shards={len(shards)}/{plan.n_shards} [{platform}]")
    csr = hwk.edges_to_csr(src, dst, w, G)
    kw = dict(seed=4242, csr=csr)

    t0 = time.perf_counter()
    dwk.walk_shard_device(src, dst, w, G, plan, shards[0], **kw)
    compile_s = time.perf_counter() - t0

    rows = 0
    host_s = dev_s = 0.0
    bit_identical = True
    for s in shards:
        ht = dt = float("inf")
        host = device = None
        for _ in range(DEVICE_WALK_TIMING_REPS):
            t0 = time.perf_counter()
            host = hwk.walk_shard(src, dst, w, G, plan, s, **kw)
            ht = min(ht, time.perf_counter() - t0)
            t0 = time.perf_counter()
            device = dwk.walk_shard_device(src, dst, w, G, plan, s, **kw)
            dt = min(dt, time.perf_counter() - t0)
        host_s += ht
        dev_s += dt
        rows += int(host.shape[0])
        if host.tobytes() != device.tobytes():
            bit_identical = False
            note(f"BIT MISMATCH on shard {s} — A/B void")
            break
    sampler = {
        "bit_identical": bit_identical,
        "rows_sampled": rows, "shards_timed": len(shards),
        "host_paths_per_s": (rows / host_s) if host_s > 0 else None,
        "device_paths_per_s": (rows / dev_s) if dev_s > 0 else None,
        "device_vs_host": (host_s / dev_s) if dev_s > 0 else None,
        "device_compile_s": compile_s,
    }
    note(f"host {sampler['host_paths_per_s']:.0f} paths/s, device "
         f"{sampler['device_paths_per_s']:.0f} paths/s "
         f"(x{sampler['device_vs_host']:.2f}), compile {compile_s:.2f}s, "
         f"bit_identical={bit_identical}")

    # -- (b) fused feed A/B: native ring vs --device-feed --------------
    Gf = DEVICE_FEED_GENES
    def _grp(seed):
        rr = np.random.default_rng(seed)
        Ef = Gf * 6
        return (rr.integers(0, Gf, Ef).astype(np.int32),
                rr.integers(0, Gf, Ef).astype(np.int32),
                rr.random(Ef, dtype=np.float32))
    feed_kw = dict(
        groups=[_grp(1), _grp(2)], n_genes=Gf,
        genes=np.array([f"g{i}" for i in range(Gf)]), hidden=32,
        learning_rate=0.05, max_epochs=DEVICE_FEED_EPOCHS, seed=3,
        walk_seed=5, len_path=20, reps=2, compute_dtype="float32")

    def _arm(tag, **over):
        marks = []
        t_start = time.perf_counter()
        res = train_cbow_streaming(
            **feed_kw, **over,
            check=lambda: marks.append(time.perf_counter() - t_start))
        wall = time.perf_counter() - t_start
        # marks[0] is the epoch-0 entry tick; marks[1] fires once the
        # FIRST shard is ready at the trainer (time-to-first-update).
        ttfu = marks[1] if len(marks) > 1 else None
        note(f"{tag}: wall {wall:.2f}s ttfu {ttfu:.3f}s")
        return res, wall, ttfu

    ring_res, ring_wall, ring_ttfu = _arm("ring (native)")
    _arm("ring (device sampler)", walker_backend="device")
    fused_res, fused_wall, fused_ttfu = _arm(
        "device feed", walker_backend="device", device_feed=True)
    feed_ok = (np.asarray(ring_res.train.w_ih).tobytes()
               == np.asarray(fused_res.train.w_ih).tobytes())
    feed = {
        "n_genes": Gf, "epochs": DEVICE_FEED_EPOCHS,
        "ring_wall_s": ring_wall, "device_feed_wall_s": fused_wall,
        "ring_ttfu_s": ring_ttfu, "device_feed_ttfu_s": fused_ttfu,
        "ttfu_delta_s": ((ring_ttfu - fused_ttfu)
                         if ring_ttfu is not None and fused_ttfu is not None
                         else None),
        "h2d_bytes_saved": int(fused_res.stats.h2d_bytes_saved),
        "device_ring_puts": int(fused_res.stats.shards_emitted),
        "outputs_bit_identical": feed_ok,
    }

    # -- (c) chip sweep: honest nulls off-chip --------------------------
    chip = []
    for chip_g in (65536, 262144):
        metric = f"device_walk_paths_per_s_g{chip_g}"
        if not on_chip:
            chip.append({
                "metric": metric, "value": None, "unit": "paths/s",
                "skipped": "no accelerator attached — CPU dispatch "
                           "timings cannot stand in for on-chip "
                           "sampling + H2D elision; a watcher run on "
                           "hardware (tools/watch_loop.sh chip battery) "
                           "must fill this line"})
            continue
        rc = np.random.default_rng(chip_g)
        Ec = chip_g * 4
        sc = rc.integers(0, chip_g, Ec).astype(np.int32)
        dc = rc.integers(0, chip_g, Ec).astype(np.int32)
        wc = rc.random(Ec, dtype=np.float32)
        pc = hwk.plan_shards(chip_g, 1, 0, len_path=L)
        dwk.walk_shard_device(sc, dc, wc, chip_g, pc, 0, seed=1)  # warm
        t0 = time.perf_counter()
        out = dwk.walk_shard_device(sc, dc, wc, chip_g, pc, 0, seed=1)
        dt = time.perf_counter() - t0
        chip.append({"metric": metric,
                     "value": out.shape[0] / dt if dt > 0 else None,
                     "unit": "paths/s", "skipped": None})

    ok = bool(bit_identical and feed_ok
              and feed["device_ring_puts"] == 0
              and feed["h2d_bytes_saved"] > 0)
    return {
        "bench": "device_walk", "ok": ok, "platform": platform,
        "config": {"n_genes": G, "n_edges": E, "len_path": L,
                   "walk_reps": wreps,
                   "timing_reps": DEVICE_WALK_TIMING_REPS},
        "sampler": sampler, "feed": feed, "chip": chip,
        "note": "CPU A/B bounds sampler dispatch overhead only; the "
                "H2D-elision benefit is chip-shaped, so chip lines are "
                "watcher-gated explicit nulls off-chip, never faked. "
                "paths/s is void unless bit_identical — the rows are "
                "byte-compared against the host pool on every timed "
                "shard.",
    }


def _device_walk() -> None:
    """Standalone mode: run the on-device walk sampling A/B and refresh
    the committed artifact."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _device_walk_line(note)
    print(json.dumps(line), flush=True)
    repo = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(repo, DEVICE_WALK_ARTIFACT), "w") as f:
        json.dump({"line": line, "code_key": _current_code_key(repo),
                   "written_by": "bench.py --_device_walk"}, f, indent=1)
    note(f"wrote {DEVICE_WALK_ARTIFACT}")
    if not line["ok"]:
        sys.exit(1)


def _shard_scale_line(note) -> dict:
    """Million-node shard-scale sweep — ROADMAP item 2's headline.

    For each ``genes:ranks`` cell of ``SHARD_SCALE_GRID``: stream the
    scale-free synthetic to disk (data/synth.write_synth_graph_streamed,
    never materializing the graph), then run a REAL ``ranks``-process
    fleet of tests/shard_worker.py — sharded walk sampling over the
    chunked KV transport, the split [G/R, H] trainer, partitioned
    k-means/t-scores — and record every rank's own peak RSS (ru_maxrss).

    1-rank cells route through the EXACT unsharded code paths (the
    byte-identity contract), so they double as the measured unsharded
    anchors — what one host actually pays at that scale, process
    overhead and transients included, not just the analytic table
    bytes.

    Three claims measured on the spot:

    (a) **Flat diagonal**: across MULTI-RANK cells with equal
        genes/ranks the per-rank peak RSS must stay within
        ``SHARD_SCALE_RSS_FLAT`` — a graph R x larger at R x ranks
        costs each rank ~the same memory.
    (b) **Fit vs the unsharded run**: at the largest scale, every
        sharded rank's peak RSS sits below the MEASURED single-host
        unsharded run's peak at the same scale (and is compared to the
        analytic unsharded trainer-state bytes, 4 x [G, H] f32, for
        reference).
    (c) **1-rank byte identity**: at the smallest scale, the sharded
        single-rank cell's output files are byte-identical to a plain
        unsharded streaming run (the tests/test_shard.py contract,
        re-verified at bench scale).

    No jax in THIS process — every measurement runs in worker children.
    """
    import shutil
    import socket
    import tempfile

    from g2vec_tpu.data.synth import (SynthGraphSpec,
                                      write_synth_graph_streamed)

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "shard_worker.py")
    grid = [(int(g), int(r)) for g, r in
            (cell.split(":") for cell in SHARD_SCALE_GRID.split(","))]
    hidden = SHARD_SCALE_HIDDEN

    def rank_env(port: int, process_id: int, n_ranks: int) -> dict:
        drop = ("PALLAS_AXON", "AXON_", "TPU_", "JAX_", "XLA_", "LIBTPU",
                "PJRT_")
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(drop)}
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "axon" not in p.lower()]
        env["PYTHONPATH"] = os.pathsep.join([repo] + parts)
        env["JAX_PLATFORMS"] = "cpu"
        env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
        env["G2VEC_PROCESS_ID"] = str(process_id)
        env["G2VEC_NUM_PROCESSES"] = str(n_ranks)
        return env

    def launch(td: str, cfg: dict, n_ranks: int) -> list:
        cfg_path = os.path.join(td, f"cfg{n_ranks}.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, worker, cfg_path],
            env=rank_env(port, i, n_ranks), cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(n_ranks)]
        parsed = []
        try:
            for i, p in enumerate(procs):
                stdout, stderr = p.communicate(
                    timeout=SHARD_SCALE_CELL_TIMEOUT)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"shard-scale rank {i}/{n_ranks} rc="
                        f"{p.returncode}: {stderr[-400:]}")
                parsed.append(json.loads(
                    stdout.strip().splitlines()[-1]))
        finally:
            for q in procs:             # a dead sibling must not wedge
                if q.poll() is None:
                    q.kill()
        return parsed

    def cell_cfg(paths: dict, out: str, n_ranks: int) -> dict:
        cfg = dict(
            expression_file=paths["expression"],
            clinical_file=paths["clinical"],
            network_file=paths["network"], result_name=out,
            lenPath=12, numRepetition=2, sizeHiddenlayer=hidden,
            epoch=2, numBiomarker=10, seed=11, compute_dtype="float32",
            walker_backend="native", train_mode="streaming",
            stream_patience=2, shard_paths=256,
            walk_starts=SHARD_SCALE_STARTS, stream_eval_rows=512,
            graph_shards=max(n_ranks, 1), embed_shards=max(n_ranks, 1))
        if n_ranks > 1:
            cfg.update(distributed=True,
                       fleet_watchdog_deadline=float(
                           SHARD_SCALE_CELL_TIMEOUT))
        return cfg

    def read_outputs(result_name: str) -> dict:
        out = {}
        for suffix in ("_biomarkers.txt", "_lgroups.txt", "_vectors.txt"):
            with open(result_name + suffix, "rb") as f:
                out[suffix] = f.read()
        return out

    cells = []
    byte_identical = None
    with tempfile.TemporaryDirectory() as td:
        data = {}
        for n_genes in sorted({g for g, _ in grid}):
            t0 = time.time()
            spec = SynthGraphSpec(n_genes=n_genes, n_good=8, n_poor=8,
                                  seed=5)
            data[n_genes] = write_synth_graph_streamed(
                spec, os.path.join(td, f"g{n_genes}"))
            note(f"shard-scale data: {n_genes} genes, "
                 f"{data[n_genes]['n_edges']} edges streamed to disk in "
                 f"{time.time() - t0:.1f}s")
        for n_genes, n_ranks in grid:
            out = os.path.join(td, f"c{n_genes}x{n_ranks}", "RES")
            os.makedirs(os.path.dirname(out), exist_ok=True)
            t0 = time.time()
            parsed = launch(td, cell_cfg(data[n_genes], out, n_ranks),
                            n_ranks)
            wall = time.time() - t0
            rss_mb = [p["rss_kb"] // 1024 for p in parsed]
            cells.append({
                "n_genes": n_genes, "n_ranks": n_ranks,
                "wall_s": round(wall, 1),
                "per_rank_peak_rss_mb": rss_mb,
                "max_rank_rss_mb": max(rss_mb),
                "acc_val": round(parsed[0]["acc_val"], 4),
                "n_paths": parsed[0]["n_paths"]})
            note(f"shard-scale cell {n_genes}x{n_ranks}: {wall:.1f}s, "
                 f"per-rank peak RSS {rss_mb} MB, "
                 f"acc {parsed[0]['acc_val']:.3f}, "
                 f"{parsed[0]['n_paths']} paths")
            if n_ranks == 1 and byte_identical is None:
                # (c): plain unsharded twin at the same scale.
                ref = os.path.join(td, f"ref{n_genes}", "RES")
                os.makedirs(os.path.dirname(ref), exist_ok=True)
                cfg = cell_cfg(data[n_genes], ref, 1)
                cfg.update(graph_shards=0, embed_shards=0)
                launch(td, cfg, 1)
                byte_identical = read_outputs(out) == read_outputs(ref)
                note(f"shard-scale 1-rank byte identity at {n_genes} "
                     f"genes: {byte_identical}")
        shutil.rmtree(td, ignore_errors=True)

    # (a) the diagonal: equal genes-per-rank MULTI-RANK cells must cost
    # ~equal per-rank RSS (1-rank cells are the unsharded anchors and
    # have a structurally different profile — full-width buffers).
    sharded = [c for c in cells if c["n_ranks"] > 1]
    anchors = {c["n_genes"]: c for c in cells if c["n_ranks"] == 1}
    diagonals = {}
    for c in sharded:
        diagonals.setdefault(c["n_genes"] // c["n_ranks"], []).append(c)
    diag_detail = []
    flat_ratio = 1.0
    for key in sorted(diagonals):
        group = sorted(diagonals[key], key=lambda c: c["n_genes"])
        if len(group) < 2:
            continue
        rss = [c["max_rank_rss_mb"] for c in group]
        ratio = round(max(rss) / max(min(rss), 1), 3)
        flat_ratio = max(flat_ratio, ratio)
        diag_detail.append({
            "genes_per_rank": key,
            "cells": [f"{c['n_genes']}x{c['n_ranks']}" for c in group],
            "max_rank_rss_mb": rss, "ratio": ratio})
    # (b) the largest sharded cell vs the MEASURED unsharded run at the
    # same scale (plus the analytic trainer-state bytes for reference).
    big = max(sharded, key=lambda c: c["n_genes"])
    anchor = anchors.get(big["n_genes"])
    unsharded_run_mb = anchor["max_rank_rss_mb"] if anchor else None
    unsharded_state_mb = 4 * big["n_genes"] * hidden * 4 // (1024 * 1024)
    return {
        "metric": "shard_scale_per_rank_peak_rss_mb",
        "value": big["max_rank_rss_mb"], "unit": "MB",
        "vs_baseline": (round(unsharded_run_mb
                              / max(big["max_rank_rss_mb"], 1), 2)
                        if unsharded_run_mb else None),
        "unsharded_run_rss_mb": unsharded_run_mb,
        "fits_under_unsharded_run":
            (big["max_rank_rss_mb"] < unsharded_run_mb
             if unsharded_run_mb else None),
        "unsharded_trainer_state_mb": unsharded_state_mb,
        "hidden": hidden, "walk_starts": SHARD_SCALE_STARTS,
        "cells": cells,
        "diagonals": diag_detail,
        "diagonal_rss_flat_ratio": flat_ratio,
        "diagonal_flat_ok": flat_ratio <= SHARD_SCALE_RSS_FLAT,
        "single_rank_byte_identical": byte_identical,
        "note": "real multi-process fleets over the chunked KV transport "
                "(sharded walks + split [G/R, H] trainer + partitioned "
                "k-means/t-scores); vs_baseline = the MEASURED unsharded "
                "single-host run's peak RSS at the largest scale over the "
                "largest sharded cell's per-rank peak",
    }


def _shard_scale() -> None:
    """Standalone mode: measure the shard-scale sweep and (with
    G2VEC_BENCH_SHARD_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _shard_scale_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_SHARD_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, SHARD_SCALE_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_shard_scale"}, f,
                      indent=1)
        note(f"wrote {SHARD_SCALE_ARTIFACT}")
    if not (line["fits_under_unsharded_run"] and line["diagonal_flat_ok"]
            and line["single_rank_byte_identical"] is not False):
        sys.exit(1)


def _edge_ab_line(note) -> dict:
    """Edge-partition A/B at one scale: ``full`` (graph-sharded fleet,
    every rank holds the whole CSR) vs ``handoff`` vs ``halo``
    (owner-range CSRs; boundary walks shipped vs boundary rows
    replicated) — ``EDGE_AB_GENES`` genes across ``EDGE_AB_RANKS`` real
    worker processes each.

    Measured per arm: per-rank graph bytes (the tentpole — EXACT from
    each rank's own ``edge_stats`` result line for the partitioned
    arms, analytic for the full arm), per-rank peak RSS, wall time, and
    end-to-end path throughput. Plus the contracts on the spot: the
    partitioned arms' output files must be byte-identical to EACH OTHER
    (same walks, different boundary strategy), and the edge arms run
    under ``G2VEC_FORBID_FULL_NETWORK`` so any touch of the
    unpartitioned reader fails the arm outright. The ``halo`` events
    carry the replication overhead that PROFILE.md's
    memory-vs-latency attribution cites.

    No jax in THIS process — every measurement runs in worker children.
    """
    import shutil
    import socket
    import tempfile

    from g2vec_tpu.data.synth import (SynthGraphSpec,
                                      write_synth_graph_streamed)
    from g2vec_tpu.io.readers import FORBID_FULL_NETWORK_ENV

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tests", "shard_worker.py")
    n_genes, n_ranks = EDGE_AB_GENES, EDGE_AB_RANKS

    def rank_env(port: int, process_id: int, extra: dict) -> dict:
        drop = ("PALLAS_AXON", "AXON_", "TPU_", "JAX_", "XLA_", "LIBTPU",
                "PJRT_")
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(drop)}
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "axon" not in p.lower()]
        env["PYTHONPATH"] = os.pathsep.join([repo] + parts)
        env["JAX_PLATFORMS"] = "cpu"
        env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
        env["G2VEC_PROCESS_ID"] = str(process_id)
        env["G2VEC_NUM_PROCESSES"] = str(n_ranks)
        env.update(extra)
        return env

    def launch(td: str, arm: str, cfg: dict, extra: dict) -> list:
        cfg_path = os.path.join(td, f"{arm}_cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, worker, cfg_path],
            env=rank_env(port, i, extra), cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for i in range(n_ranks)]
        parsed = []
        try:
            for i, p in enumerate(procs):
                stdout, stderr = p.communicate(timeout=EDGE_AB_TIMEOUT)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"edge-ab {arm} rank {i}/{n_ranks} rc="
                        f"{p.returncode}: {stderr[-400:]}")
                parsed.append(json.loads(stdout.strip().splitlines()[-1]))
        finally:
            for q in procs:             # a dead sibling must not wedge
                if q.poll() is None:
                    q.kill()
        return parsed

    def arm_cfg(td: str, arm: str, paths: dict, mode: str) -> dict:
        out = os.path.join(td, arm, "RES")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        cfg = dict(
            expression_file=paths["expression"],
            clinical_file=paths["clinical"],
            network_file=paths["network"], result_name=out,
            lenPath=12, numRepetition=2, sizeHiddenlayer=EDGE_AB_HIDDEN,
            epoch=2, numBiomarker=10, seed=11, compute_dtype="float32",
            walker_backend="native", train_mode="streaming",
            stream_patience=2, shard_paths=256,
            walk_starts=EDGE_AB_STARTS, stream_eval_rows=512,
            graph_shards=n_ranks, embed_shards=n_ranks,
            edge_partition=mode)
        if n_ranks > 1:
            cfg.update(distributed=True,
                       fleet_watchdog_deadline=float(EDGE_AB_TIMEOUT))
        return cfg

    def read_outputs(result_name: str) -> dict:
        out = {}
        for suffix in ("_biomarkers.txt", "_lgroups.txt", "_vectors.txt"):
            with open(result_name + suffix, "rb") as f:
                out[suffix] = f.read()
        return out

    arms = {}
    with tempfile.TemporaryDirectory() as td:
        spec = SynthGraphSpec(n_genes=n_genes, n_good=8, n_poor=8, seed=5)
        t0 = time.time()
        flat = write_synth_graph_streamed(
            spec, os.path.join(td, "flat"), prefix="eg")
        part = write_synth_graph_streamed(
            spec, os.path.join(td, "part"), prefix="eg",
            partitions=n_ranks)
        note(f"edge-ab data: {n_genes} genes, {flat['n_edges']} edges, "
             f"flat + {n_ranks}-way partitioned emission in "
             f"{time.time() - t0:.1f}s")
        for arm, mode, paths, extra in (
                ("full", "off", flat, {}),
                ("handoff", "handoff", part, {FORBID_FULL_NETWORK_ENV: "1"}),
                ("halo", "halo", part, {FORBID_FULL_NETWORK_ENV: "1"})):
            t0 = time.time()
            parsed = launch(td, arm, arm_cfg(td, arm, paths, mode), extra)
            wall = time.time() - t0
            rss_mb = [p["rss_kb"] // 1024 for p in parsed]
            rec = {
                "mode": mode, "wall_s": round(wall, 1),
                "per_rank_peak_rss_mb": rss_mb,
                "max_rank_rss_mb": max(rss_mb),
                "acc_val": round(parsed[0]["acc_val"], 4),
                "n_paths": parsed[0]["n_paths"],
                "paths_per_s": round(parsed[0]["n_paths"] / wall, 1)}
            if mode == "off":
                # Every rank holds the whole graph: both groups' CSRs,
                # ~(G+1) int64 indptr + 8 B/edge (int32 index + f32
                # weight) each. Analytic — the full path has no
                # owner-range accounting to report.
                rec["per_rank_graph_bytes"] = [
                    2 * 8 * (n_genes + 1) + 8 * parsed[0]["n_edges"]
                    ] * n_ranks
                rec["graph_bytes_analytic"] = True
            else:
                rec["per_rank_graph_bytes"] = [
                    p["edge_stats"]["csr_bytes"] for p in parsed]
                rec["per_rank_owned_edges"] = [
                    p["edge_stats"]["owned_edges"] for p in parsed]
                if mode == "halo":
                    rec["per_rank_halo_bytes"] = [
                        p["edge_stats"]["halo_bytes"] for p in parsed]
                    rec["halo_overhead_ratio"] = [
                        round(p["edge_stats"]["halo_bytes"]
                              / max(1, 8 * p["edge_stats"]["owned_edges"]),
                              4) for p in parsed]
                if "rounds" in parsed[0]["edge_stats"]:
                    rec["handoff"] = {
                        k: parsed[0]["edge_stats"][k]
                        for k in ("shards", "rounds", "states_sent",
                                  "batches", "peak_in_flight")}
            rec["max_rank_graph_mb"] = round(
                max(rec["per_rank_graph_bytes"]) / 2 ** 20, 1)
            arms[arm] = rec
            note(f"edge-ab {arm}: {wall:.1f}s, per-rank graph "
                 f"{[round(b / 2 ** 20, 1) for b in rec['per_rank_graph_bytes']]}"
                 f" MB, peak RSS {rss_mb} MB, acc {rec['acc_val']:.3f}")
        identical = (read_outputs(os.path.join(td, "handoff", "RES"))
                     == read_outputs(os.path.join(td, "halo", "RES")))
        note(f"edge-ab handoff == halo outputs: {identical}")
        shutil.rmtree(td, ignore_errors=True)

    full_b = max(arms["full"]["per_rank_graph_bytes"])
    edge_b = max(arms["handoff"]["per_rank_graph_bytes"])
    return {
        "metric": "edge_partition_per_rank_graph_mb",
        "value": arms["handoff"]["max_rank_graph_mb"], "unit": "MB",
        "vs_baseline": round(full_b / max(edge_b, 1), 2),
        "n_genes": n_genes, "n_ranks": n_ranks,
        "hidden": EDGE_AB_HIDDEN, "walk_starts": EDGE_AB_STARTS,
        "arms": arms,
        "handoff_equals_halo": identical,
        "acc_band_vs_full": round(abs(arms["handoff"]["acc_val"]
                                      - arms["full"]["acc_val"]), 4),
        "note": "real multi-process fleets; partitioned arms read ONLY "
                "their owned manifest parts (G2VEC_FORBID_FULL_NETWORK "
                "armed) and hold owner-range CSRs; vs_baseline = full "
                "arm's per-rank graph bytes over handoff's; paths/s is "
                "end-to-end (walk production overlaps training)",
    }


def _edge_ab() -> None:
    """Standalone mode: measure the edge-partition A/B and (with
    G2VEC_BENCH_EDGE_WRITE=1) refresh the committed artifact."""
    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    line = _edge_ab_line(note)
    print(json.dumps(line), flush=True)
    if os.environ.get("G2VEC_BENCH_EDGE_WRITE") == "1":
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, EDGE_AB_ARTIFACT), "w") as f:
            json.dump({"line": line, "code_key": _current_code_key(repo),
                       "written_by": "bench.py --_edge_ab"}, f, indent=1)
        note(f"wrote {EDGE_AB_ARTIFACT}")
    if not line["handoff_equals_halo"]:
        sys.exit(1)


def _run_measure_child(budget: int, child_env: dict,
                       first_metric_cutoff: int,
                       cmd: "list | None" = None) -> tuple:
    """Run the measure child, watching its stdout as it streams.

    Returns (stdout, stderr, fail) where fail is None on rc=0. Beyond the
    plain ``budget`` kill, a child that has emitted no metric line by
    ``first_metric_cutoff`` is killed early — it is wedged on a dead
    backend, and the saved window funds the caller's one retry. Callers
    pass cutoff == budget to disable the early kill (non-TPU backends).
    ``cmd`` overrides the measure invocation (tests only).
    """
    import tempfile

    with tempfile.TemporaryFile() as fo, tempfile.TemporaryFile() as fe:
        proc = subprocess.Popen(
            cmd or [sys.executable, os.path.abspath(__file__), "--_measure"],
            stdout=fo, stderr=fe, env=child_env)

        def snapshot(f) -> str:
            # os.pread: the child WRITES through the same open file
            # description, so the parent must never seek it — a seek(0)
            # would move the child's write position and make its next
            # flush overwrite the lines already captured.
            return os.pread(f.fileno(), 1 << 26, 0).decode(errors="replace")

        t0 = time.time()
        fail = None
        metric_seen = False
        while True:
            rc = proc.poll()
            if rc is not None:
                fail = f"rc={rc}" if rc != 0 else None
                break
            elapsed = time.time() - t0
            if elapsed > budget:
                proc.kill()
                proc.wait()
                fail = f"measurement exceeded {budget}s"
                break
            if not metric_seen and elapsed > first_metric_cutoff:
                metric_seen = _has_real_metric(snapshot(fo))
                if not metric_seen:
                    proc.kill()
                    proc.wait()
                    fail = (f"no metric after {first_metric_cutoff}s "
                            f"(backend wedged)")
                    break
            time.sleep(2)
        return snapshot(fo), snapshot(fe), fail


def _has_real_metric(out: str) -> bool:
    """True iff a complete metric line with a non-null value was relayed."""
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("metric") and d.get("value") is not None:
                return True
    return False


def _apply_platform_override() -> None:
    """G2VEC_BENCH_PLATFORM=cpu: force the platform IN-PROCESS.

    Smoke-testing hook. Deliberately not JAX_PLATFORMS-in-env: with a
    wedged axon tunnel, a platform env var present at interpreter startup
    makes the sitecustomize's plugin registration hang `import jax`
    itself; the in-process sequence (env + config.update before first
    backend use) never dials the tunnel.
    """
    plat = os.environ.get("G2VEC_BENCH_PLATFORM")
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        import jax

        jax.config.update("jax_platforms", plat)


def _probe() -> None:
    """Child: bounded backend initialization check."""
    _apply_platform_override()
    import jax

    devs = jax.devices()
    print(json.dumps({"platform": jax.default_backend(),
                      "n_devices": len(devs),
                      "device0": str(devs[0])}))


# --------------------------------------------------------------------------
# Measurement child (runs only after the probe proved the backend alive).
# --------------------------------------------------------------------------

def make_paths(rng, n_paths: int, n_genes: int):
    """Multi-hot paths with planted good/poor gene blocks (~40 genes/path,
    matching the reference's mean path occupancy at lenPath=80)."""
    import numpy as np

    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    genes_per_path = 40
    idx = rng.integers(0, half, size=(n_paths, genes_per_path))
    idx += labels[:, None] * half
    np.put_along_axis(paths, idx, 1, axis=1)
    return paths, labels


def _peak_flops() -> float:
    return _PEAK_FLOPS.get(os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"), 197e12)


def _peak_hbm_bytes_per_sec() -> float:
    return _PEAK_HBM.get(os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"), 819e9)


def _epoch_flops(n_paths: int, n_genes: int, hidden: int) -> int:
    """Matmul FLOPs the TRAINER actually executes per epoch after the
    eval-train fold (trainer.py): grad fwd X@W_ih (2*M*G*H) + dW = X^T@dH
    (2*M*G*H) on the train split — the train-accuracy eval rides the next
    epoch's grad forward — plus one val eval fwd; the [_, H] @ [H, 1]
    output matmuls are negligible. (The reference's epoch additionally
    re-runs a full train-split eval forward, ref: G2Vec.py:264-267 — its
    per-epoch work is 2*G*H*(3*m_tr + m_val); paths/s comparisons against
    the transcript are wall-clock per epoch and unaffected.)"""
    m_tr = int(n_paths * (1 - VAL_FRACTION))
    m_val = n_paths - m_tr
    return 2 * n_genes * hidden * (2 * m_tr + m_val)


def _bench_train(paths, labels, hidden: int, measure_epochs: int,
                 use_pallas=None, **train_kwargs) -> tuple:
    """(sec/epoch, mfu) of the device-resident trainer at these shapes.
    ``train_kwargs`` pass through to train_cbow (the superstep A/B hands
    ``epoch_superstep`` here — same trainer, different chunk program)."""
    import numpy as np

    from g2vec_tpu.train.trainer import DEFAULT_CHUNK, train_cbow

    common = dict(hidden=hidden, learning_rate=0.005,
                  val_fraction=VAL_FRACTION, compute_dtype="bfloat16", seed=0,
                  use_pallas=use_pallas, **train_kwargs)

    # Warmup call: compiles the chunk program. The timed run's program
    # shape is min(DEFAULT_CHUNK, measure_epochs) — warm up with exactly
    # that, or the measured first chunk would contain a fresh compile.
    train_cbow(paths, labels,
               max_epochs=WARMUP_EPOCHS or min(DEFAULT_CHUNK, measure_epochs),
               **common)
    res = train_cbow(paths, labels, max_epochs=measure_epochs, **common)

    epoch_secs = [h["secs"] for h in res.history]
    steady = epoch_secs[DEFAULT_CHUNK:]   # first chunk absorbs the transfer
    if not steady:           # early stop in the first chunk — use what we have
        steady = epoch_secs
    sec_per_epoch = float(np.median(steady))
    mfu = (_epoch_flops(paths.shape[0], paths.shape[1], hidden)
           / sec_per_epoch / _peak_flops())
    return sec_per_epoch, mfu


def _load_bench_edges():
    """(src, dst, w, n_genes): the real bundled network with synthetic
    |PCC| weights, or a scale-matched fallback. NumPy only — the host-only
    fallback path must never touch jax (a wedged tunnel can hang its
    import-time plugin registration)."""
    import numpy as np

    rng = np.random.default_rng(42)
    if os.path.exists(REFERENCE_NETWORK):
        src_names, dst_names = [], []
        with open(REFERENCE_NETWORK) as f:
            next(f)
            for line in f:
                parts = line.rstrip().split("\t")
                if len(parts) == 2:
                    src_names.append(parts[0])
                    dst_names.append(parts[1])
        genes = sorted(set(src_names) | set(dst_names))
        g2i = {g: i for i, g in enumerate(genes)}
        src = np.fromiter((g2i[g] for g in src_names), np.int32)
        dst = np.fromiter((g2i[g] for g in dst_names), np.int32)
        # The transcript reports 216,540 of 298,799 edges surviving the
        # |PCC| > 0.5 filter (README.md:28): keep the same fraction.
        keep = rng.random(src.size) < (216540 / 298799)
        src, dst = src[keep], dst[keep]
        n_genes = len(genes)
    else:
        # Fallback: same scale, power-law-ish out-degrees. Env-shrinkable
        # so CPU smoke/proof runs can walk the full stage battery without
        # spending the budget on one device-walker stage (chip rounds
        # have the real network mounted and never read this).
        n_genes = int(os.environ.get("G2VEC_BENCH_FALLBACK_GENES", "9904"))
        n_edges = max(n_genes, int(216540 * n_genes / 9904))
        p = (1.0 / np.arange(1, n_genes + 1)) ** 0.8
        src = rng.choice(n_genes, size=n_edges, p=p / p.sum()).astype(np.int32)
        dst = rng.integers(0, n_genes, size=n_edges).astype(np.int32)
    w = rng.uniform(0.5001, 1.0, size=src.size).astype(np.float32)
    return src, dst, w, n_genes


def _restrict_bench_edges(src, dst, w, n_genes: int,
                          target: int = 7523, seed: int = 7):
    """(src, dst, w, n_genes) restricted to ``target`` genes — the
    stage-3 walk shape. The pipeline walks the expression∩network gene
    set (7,523 genes in the reference transcript, README.md:27), not the
    full 9,904-gene network; the intersection is topology-blind (which
    genes were assayed has nothing to do with the graph), so a seeded
    uniform subset is the faithful stand-in. Edges with both endpoints
    kept are remapped to the compact [0, target) index space. No jax."""
    import numpy as np

    if n_genes <= target:
        return src, dst, w, n_genes
    rng = np.random.default_rng(seed)
    keep = np.sort(rng.choice(n_genes, size=target, replace=False))
    remap = np.full(n_genes, -1, dtype=np.int64)
    remap[keep] = np.arange(target)
    m = (remap[src] >= 0) & (remap[dst] >= 0)
    return (remap[src[m]].astype(np.int32), remap[dst[m]].astype(np.int32),
            np.asarray(w)[m], target)


def _load_bench_network():
    """(table_on_device, nbr_idx, nbr_w, n_genes, edges): device form of
    :func:`_load_bench_edges` for the JAX walker stages."""
    import jax
    import jax.numpy as jnp

    from g2vec_tpu.ops.graph import neighbor_table

    src, dst, w, n_genes = _load_bench_edges()
    nbr_idx, nbr_w = neighbor_table(src, dst, w, n_genes)
    table = (jax.device_put(jnp.asarray(nbr_idx, jnp.int32)),
             jax.device_put(jnp.asarray(nbr_w, jnp.float32)))
    return table, nbr_idx, nbr_w, n_genes, (src, dst, w)


def _reference_walk_baseline(indptr, indices, weights, n_genes: int,
                             len_path: int, budget_s: "float | None" = None,
                             min_walks: int = 40) -> tuple:
    """(walks/s, n_sampled) of the reference's own algorithm on this host.

    A faithful re-creation of generate_randomPath's per-step work
    (ref: G2Vec.py:328-346): copy the current node's dense transition row,
    zero the visited entries, renormalize, np.random.choice. Start nodes are
    DEGREE-STRATIFIED (every k-th gene of the degree-sorted order, shuffled)
    so hub and leaf walk costs are both represented — VERDICT r2 weak #7:
    a first-come sample under-weights hubs on a scale-free graph.
    Takes the CSR form so the host-only fallback can run it without jax.
    ``budget_s`` defaults to BASELINE_BUDGET (12 s; the toy-scale
    subprocess tests shrink it via G2VEC_BENCH_BASELINE_BUDGET).
    """
    import numpy as np

    if budget_s is None:
        budget_s = BASELINE_BUDGET
    dense_rows = {}

    def row(i):
        r = dense_rows.get(i)
        if r is None:
            r = np.zeros(n_genes, dtype=np.float64)
            lo, hi = indptr[i], indptr[i + 1]
            r[indices[lo:hi]] = weights[lo:hi]
            dense_rows[i] = r
        return r

    rng = np.random.default_rng(7)
    by_degree = np.argsort(np.diff(indptr))
    strata = by_degree[:: max(1, n_genes // 512)]     # ~512 across spectrum
    starts = rng.permutation(strata)
    t0 = time.time()
    done = 0
    for s in starts:
        path = [int(s)]
        current = int(s)
        for _ in range(len_path - 1):
            prob = row(current).copy()          # the reference's deepcopy
            prob[path] = 0.0
            total = prob.sum()
            if total <= 0.0:
                break
            current = int(rng.choice(n_genes, p=prob / total))
            path.append(current)
        done += 1
        if time.time() - t0 > budget_s and done >= min_walks:
            break
    return done / (time.time() - t0), done


def _bench_walker(table, n_genes: int, len_path: int, reps: int) -> dict:
    import jax

    from g2vec_tpu.ops.walker import generate_path_set

    key = jax.random.key(0)
    total = n_genes * reps

    def run(batch: int) -> dict:
        # Warmup at the REAL launch shape: the timed run dispatches
        # [batch]-walker programs; a reps=1 warmup at walker_batch=batch
        # pads to that exact shape, so the compile (and one full-size
        # execution) happen outside the timed window.
        generate_path_set(table, key, len_path=len_path, reps=1,
                          walker_batch=batch)
        t0 = time.time()
        paths = generate_path_set(table, key, len_path=len_path, reps=reps,
                                  walker_batch=batch)
        elapsed = time.time() - t0
        return {"walks": total, "elapsed": elapsed, "batch": batch,
                "walks_per_sec": total / elapsed, "unique_paths": len(paths)}

    try:
        return run(total)          # one fused launch (the auto-size choice)
    except Exception as e:  # noqa: BLE001 — OOM/compile trouble at [total]
        print(f"# walker fused launch failed ({type(e).__name__}: "
              f"{str(e)[:200]}); retrying at batch={n_genes}",
              file=sys.stderr, flush=True)
        out = run(n_genes)         # r2-shaped sequential launches
        out["fused_launch_error"] = f"{type(e).__name__}: {e}"[:300]
        return out


def _bench_kernel_ab(hidden: int) -> dict:
    """Pallas packed matmul vs XLA dense bf16 dot, trainer fwd shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    m = pad_to_multiple(int(N_PATHS * (1 - VAL_FRACTION)), pm.ROW_BLOCK)
    g = pad_to_multiple(N_GENES, pm.LANE_BLOCK)
    rng = np.random.default_rng(0)
    x = rng.random((m, g)) < (40.0 / N_GENES)
    xp = jax.device_put(jnp.asarray(pm.pack_blockwise(x)))
    xd = jax.device_put(jnp.asarray(x, jnp.bfloat16))
    w = jax.device_put(jnp.asarray(rng.standard_normal((g, hidden)),
                                   jnp.bfloat16))

    packed = jax.jit(pm.packed_matmul)
    dense = jax.jit(lambda a, b: a @ b)

    def clock(fn, *args, iters=20):
        jax.block_until_ready(fn(*args))
        t0 = time.time()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.time() - t0) / iters * 1e3

    t_packed = clock(packed, xp, w)
    t_dense = clock(dense, xd, w)
    return {"m": m, "g": g, "h": hidden,
            "packed_ms": round(t_packed, 4), "dense_ms": round(t_dense, 4),
            "speedup": round(t_dense / t_packed, 2)}


def _bench_epoch_breakdown(paths, labels, hidden: int, epoch_sec: float,
                           interpret: bool = False,
                           superstep_k: int = 8,
                           measure_superstep: bool = True) -> dict:
    """One epoch's pieces as standalone jitted programs (trainer shapes).

    grad+update = value_and_grad over the train split + Adam apply;
    eval_val = the val accuracy forward. After the eval-train fold
    (trainer.py) the steady-state epoch is grad_update + eval_val only —
    the train eval runs once per chunk, reported here amortized
    (eval_tr_ms / DEFAULT_CHUNK). Sum vs the measured epoch shows the
    while_loop/history residual.

    Extended per-term attribution (the PR-4 roofline work):

    - ``fused_grad_eval_ms``: the fused-eval epoch program — val rows
      riding the grad pass's forward, backward sliced to the train rows
      (the trainer's custom-vjp trick, reproduced here) — vs the
      grad+standalone-eval pair it replaces (``fused_eval_saved_ms``).
    - ``superstep``: the measured per-epoch overhead recovered by
      unrolling K epochs per while_loop iteration — the REAL trainer run
      twice (K=1 is the headline ``epoch_sec``), not a model.
    - ``kernel_tiles``: the packed kernel's tile plan at each matmul
      shape this epoch runs, and whether it is the heuristic or a
      measured autotune install (``G2VEC_BENCH_KERNEL_AUTOTUNE=1`` sweeps
      the legal plans first and reports the measured table).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from g2vec_tpu.models.cbow import init_params, output_logits
    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    n_paths, n_genes = paths.shape
    g = pad_to_multiple(n_genes, pm.LANE_BLOCK)
    pivot = int(n_paths * (1 - VAL_FRACTION))

    def prep(rows):
        xb = np.zeros((pad_to_multiple(rows.shape[0], pm.ROW_BLOCK), g),
                      dtype=bool)
        xb[:rows.shape[0], :n_genes] = rows != 0
        return jax.device_put(jnp.asarray(pm.pack_blockwise(xb)))

    xtr, xval = prep(paths[:pivot]), prep(paths[pivot:])
    ytr = jax.device_put(jnp.asarray(
        np.pad(labels[:pivot].astype(np.float32),
               (0, xtr.shape[0] - pivot)).reshape(-1, 1)))
    yval = jax.device_put(jnp.asarray(
        np.pad(labels[pivot:].astype(np.float32),
               (0, xval.shape[0] - (n_paths - pivot))).reshape(-1, 1)))

    params = init_params(jax.random.key(0), g, hidden)
    tx = optax.adam(0.005)
    opt_state = tx.init(params)

    def logits_fn(p, x):
        h = pm.packed_matmul(x, p.w_ih.astype(jnp.bfloat16), interpret)
        return output_logits(h, p.w_ho, jnp.bfloat16)

    def loss(p, x, y):
        return optax.sigmoid_binary_cross_entropy(logits_fn(p, x), y).mean()

    @jax.jit
    def grad_update(p, s, x, y):
        l, g_ = jax.value_and_grad(loss)(p, x, y)
        u, s = tx.update(g_, s, p)
        return optax.apply_updates(p, u), s, l

    @jax.jit
    def evaluate(p, x, y):
        return ((logits_fn(p, x) > 0).astype(jnp.float32) == y).mean()

    def clock(fn, *args, iters=10):
        jax.block_until_ready(fn(*args))
        t0 = time.time()
        out = None
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.time() - t0) / iters * 1e3

    from g2vec_tpu.train.trainer import DEFAULT_CHUNK

    t_grad = clock(grad_update, params, opt_state, xtr, ytr)
    t_eval_tr = clock(evaluate, params, xtr, ytr)
    t_eval_val = clock(evaluate, params, xval, yval)

    # ---- fused-eval epoch program (trainer.py fused mode, measured) ----
    # One [tr+val] forward matmul; the custom-vjp backward slices x and
    # the cotangent back to the train rows — the exact program the
    # trainer's fused mode runs, so this term is the shipped math, not a
    # stand-in.
    tr_rows = int(xtr.shape[0])
    xall = jnp.concatenate([xtr, xval], axis=0)

    @jax.custom_vjp
    def fused_mm(x, w_ih):
        return pm.packed_matmul(x, w_ih, interpret)

    def _fused_fwd(x, w_ih):
        return fused_mm(x, w_ih), (x, w_ih)

    def _fused_bwd(res, dh):
        x, w_ih = res
        _, vjp = jax.vjp(
            lambda ww: pm.packed_matmul(
                jax.lax.slice_in_dim(x, 0, tr_rows), ww, interpret), w_ih)
        (dw,) = vjp(jax.lax.slice_in_dim(dh, 0, tr_rows))
        return np.zeros(x.shape, dtype=jax.dtypes.float0), dw

    fused_mm.defvjp(_fused_fwd, _fused_bwd)

    def fused_loss(p, xa, y):
        h = fused_mm(xa, p.w_ih.astype(jnp.bfloat16))
        logits_tr = output_logits(h[:tr_rows], p.w_ho, jnp.bfloat16)
        logits_val = output_logits(h[tr_rows:], p.w_ho, jnp.bfloat16)
        bce = optax.sigmoid_binary_cross_entropy(logits_tr, y).mean()
        return bce, (logits_tr, logits_val)

    @jax.jit
    def fused_step(p, s, xa, y_tr, y_val):
        (l, (lt, lv)), g_ = jax.value_and_grad(
            fused_loss, has_aux=True)(p, xa, y_tr)
        acc_val = ((lv > 0).astype(jnp.float32) == y_val).mean()
        acc_tr = ((lt > 0).astype(jnp.float32) == y_tr).mean()
        u, s = tx.update(g_, s, p)
        return optax.apply_updates(p, u), s, l, acc_val, acc_tr

    t_fused = clock(fused_step, params, opt_state, xall, ytr, yval)

    # ---- kernel tile attribution (optionally measured) ----
    m_all = int(xall.shape[0])
    autotune = None
    if os.environ.get("G2VEC_BENCH_KERNEL_AUTOTUNE") == "1":
        try:
            autotune = {
                f"m{m}": pm.autotune_packed_matmul(m, g, hidden,
                                                   interpret=interpret)
                for m in (tr_rows, m_all)}
        except Exception as e:  # noqa: BLE001 — attribution must not kill
            autotune = {"error": f"{type(e).__name__}: {e}"[:200]}
    kernel_tiles = {"tr": pm.describe_tiles(tr_rows, g, hidden),
                    "tr_val": pm.describe_tiles(m_all, g, hidden)}

    # ---- superstep A/B: the real trainer at K vs K=1 ------------------
    # Both arms measured under the SAME protocol, min-of-3 (each chunk
    # yields ONE wall sample, so single runs carry 10-20% scheduler
    # noise; min is the standard microbenchmark reducer). The compiled
    # programs are jit-cached across repeats — repeats pay epochs only.
    superstep = {"k": superstep_k, "epoch_ms_k1": None,
                 "epoch_ms_k": None, "residual_recovered_ms": None}
    if measure_superstep and superstep_k > 1:
        epochs = DEFAULT_CHUNK + max(32, DEFAULT_CHUNK // 2)

        def best_of(k, n=3):
            return min(_bench_train(paths, labels, hidden, epochs,
                                    epoch_superstep=k)[0] for _ in range(n))

        sec_1, sec_k = best_of(1), best_of(superstep_k)
        superstep["epoch_ms_k1"] = round(sec_1 * 1e3, 3)
        superstep["epoch_ms_k"] = round(sec_k * 1e3, 3)
        superstep["residual_recovered_ms"] = round((sec_1 - sec_k) * 1e3, 3)

    # Steady-state epoch = grad_update + eval_val; the train eval is one
    # per-chunk backfill (the eval-train fold, trainer.py).
    pieces = t_grad + t_eval_val + t_eval_tr / DEFAULT_CHUNK

    # Roofline account (VERDICT r4 task 2): per piece, the MINIMUM HBM
    # traffic the computation admits, and the bandwidth the measured time
    # implies against it. With h=128 output lanes the X@W matmul does only
    # ~2*h FLOPs per packed-X byte, so if implied bandwidth sits near the
    # chip peak the stage is bandwidth-bound and the MFU ceiling is
    # bytes/s * (FLOPs/byte) / peak_FLOPs — not a kernel inefficiency.
    m_tr, m_val = xtr.shape[0], xval.shape[0]
    xtr_bytes = m_tr * g // 8          # packed multi-hot, uint8
    xval_bytes = m_val * g // 8
    wih_bytes = g * hidden * 2         # bf16 compute copy
    adam_bytes = 7 * g * hidden * 4    # fp32: read p,m,v,grad; write p,m,v
    h_act_bytes = m_tr * hidden * 2    # bf16 activations, write fwd + read bwd
    grad_min_bytes = (2 * xtr_bytes        # X read fwd + bwd (dW = X^T dH)
                      + 2 * wih_bytes      # W read fwd + bwd (dH = dO W^T)
                      + 2 * h_act_bytes
                      + adam_bytes)
    eval_val_min_bytes = xval_bytes + wih_bytes
    eval_tr_min_bytes = xtr_bytes + wih_bytes
    peak_bw = _peak_hbm_bytes_per_sec()

    def gbps(nbytes, ms):
        return round(nbytes / (ms * 1e-3) / 1e9, 1) if ms > 0 else None

    roofline = {
        "hbm_peak_gbps": round(peak_bw / 1e9, 1),
        "grad_min_bytes": grad_min_bytes,
        "grad_implied_gbps": gbps(grad_min_bytes, t_grad),
        "eval_val_min_bytes": eval_val_min_bytes,
        "eval_val_implied_gbps": gbps(eval_val_min_bytes, t_eval_val),
        "eval_tr_min_bytes": eval_tr_min_bytes,
        "eval_tr_implied_gbps": gbps(eval_tr_min_bytes, t_eval_tr),
        "epoch_min_bytes": grad_min_bytes + eval_val_min_bytes
                           + eval_tr_min_bytes // DEFAULT_CHUNK,
        "bandwidth_bound_epoch_ms_floor": round(
            (grad_min_bytes + eval_val_min_bytes
             + eval_tr_min_bytes // DEFAULT_CHUNK) / peak_bw * 1e3, 3),
        # Fused-eval epoch: the val rows ride the grad forward, so the
        # standalone eval's SECOND read of W_ih disappears — only the val
        # X bytes and val activations are added to the grad pass. The
        # boundary eval (both splits) amortizes over the chunk.
        "fused_epoch_min_bytes": (
            grad_min_bytes + xval_bytes + m_val * hidden * 2
            + (xtr_bytes + xval_bytes + wih_bytes) // DEFAULT_CHUNK),
        "fused_bandwidth_bound_epoch_ms_floor": round(
            (grad_min_bytes + xval_bytes + m_val * hidden * 2
             + (xtr_bytes + xval_bytes + wih_bytes) // DEFAULT_CHUNK)
            / peak_bw * 1e3, 3),
        # Donation (trainer donate mode) does not change traffic, it
        # halves the PEAK footprint of the Adam read/write set: without
        # it the chunk call materializes fresh (params, m, v) outputs
        # beside the inputs. Informational, not a time term.
        "donate_double_buffer_bytes": 3 * (g * hidden + hidden) * 4,
    }
    return {"grad_update_ms": round(t_grad, 3),
            "eval_val_ms": round(t_eval_val, 3),
            "eval_tr_ms": round(t_eval_tr, 3),
            "eval_tr_amortized_ms": round(t_eval_tr / DEFAULT_CHUNK, 4),
            "fused_grad_eval_ms": round(t_fused, 3),
            "fused_eval_saved_ms": round(t_grad + t_eval_val - t_fused, 3),
            "superstep": superstep,
            "kernel_tiles": kernel_tiles,
            **({"kernel_autotune": autotune} if autotune else {}),
            "epoch_ms": round(epoch_sec * 1e3, 3),
            "residual_ms": round(epoch_sec * 1e3 - pieces, 3),
            "roofline": roofline}


def _measure() -> None:
    _apply_platform_override()
    import numpy as np

    deadline = time.time() + int(
        os.environ.get("G2VEC_BENCH_CHILD_BUDGET", str(CHILD_BUDGET)))

    def remaining() -> float:
        return deadline - time.time()

    def emit(d):
        print(json.dumps(d), flush=True)

    def note(msg):
        print(f"# {msg}", file=sys.stderr, flush=True)

    # ---- 1. headline trainer ----
    rng = np.random.default_rng(0)
    paths, labels = make_paths(rng, N_PATHS, N_GENES)
    sec_per_epoch, mfu = _bench_train(paths, labels, HIDDEN, MEASURE_EPOCHS)
    train_paths = int(N_PATHS * (1 - VAL_FRACTION))
    note(f"train: sec/epoch={sec_per_epoch:.4f} (baseline "
         f"{BASELINE_EPOCH_SECONDS}) mfu={mfu:.4f}")
    headline = {"metric": "cbow_train_paths_per_sec_per_chip",
                "value": round(train_paths / sec_per_epoch, 1),
                "unit": "paths/s",
                "vs_baseline": round(train_paths / sec_per_epoch
                                     / BASELINE_PATHS_PER_SEC, 2),
                "sec_per_epoch": round(sec_per_epoch, 5),
                "mfu": round(mfu, 4)}
    emit(headline)

    # ---- 2. headline walker (always runs; errors degrade to a line) ----
    walker_err = None
    baseline = None
    edges = csr = None
    try:
        from g2vec_tpu.ops.host_walker import edges_to_csr

        table, nbr_idx, nbr_w, n_genes, edges = _load_bench_network()
        csr = edges_to_csr(edges[0], edges[1], edges[2], n_genes)
        note(f"walker network: {n_genes} genes, "
             f"{int((nbr_w > 0).sum())} edges, D={nbr_idx.shape[1]}")
        res = _bench_walker(table, n_genes, LEN_PATH, WALKER_REPS)
        baseline, n_base = _reference_walk_baseline(*csr, n_genes, LEN_PATH)
        note(f"walker: {res['walks']} walks in {res['elapsed']:.2f}s -> "
             f"{res['walks_per_sec']:.0f} walks/s; {res['unique_paths']} "
             f"unique paths; host loop {baseline:.1f} walks/s "
             f"({n_base} stratified walks)")
        line = {"metric": "walker_walks_per_sec",
                "value": round(res["walks_per_sec"], 1), "unit": "walks/s",
                "vs_baseline": round(res["walks_per_sec"] / baseline, 2),
                "unique_paths": res["unique_paths"],
                "baseline_host_walks_per_sec": round(baseline, 2),
                "n_genes": n_genes, "len_path": LEN_PATH,
                "reps": WALKER_REPS, "walker_batch": res["batch"],
                "companion_metric": "walker_restricted_walks_per_sec"}
        if "fused_launch_error" in res:
            line["fused_launch_error"] = res["fused_launch_error"]
        emit(line)
    except Exception as e:  # noqa: BLE001 — degrade to an error line
        walker_err = f"{type(e).__name__}: {e}"[:500]
        emit({"metric": "walker_walks_per_sec", "value": None,
              "unit": "walks/s", "vs_baseline": None, "error": walker_err})

    # ---- 2b. native CPU walker (host-only; the fast no-accelerator path) ----
    try:
        if edges is None:
            raise RuntimeError(
                f"bench network unavailable (walker stage: {walker_err})")
        if baseline is None:
            baseline, n_base = _reference_walk_baseline(*csr, n_genes,
                                                        LEN_PATH)
        emit(_native_walker_line(
            edges[0], edges[1], edges[2], n_genes, baseline, note,
            {"note": "threaded C++ CSR sampler (ops/host_walker.py) on the "
                     "bench host; the default single-host stage-3 backend"},
            n_threads=_cli_sampler_threads()))
        # Thread-scaling + bit-identity breakdown: same host workload, so
        # chip rounds record the multicore claim too.
        emit(_mt_speedup_line(edges[0], edges[1], edges[2], n_genes, note))
    except Exception as e:  # noqa: BLE001
        emit({"metric": "walker_native_walks_per_sec", "value": None,
              "unit": "walks/s", "vs_baseline": None,
              "error": f"{type(e).__name__}: {e}"[:400]})

    # ---- optional stages, each budget-guarded ----
    # A budget-skip relays the landed in-round chip-window value (if any)
    # instead of a null — a short driver run must not erase evidence a
    # watcher battery already measured at HEAD (same rule as _hostonly).
    window_lines = _landed_window_lines(
        os.environ.get("G2VEC_BENCH_WINDOW_DIR") or None)

    def guarded(name, est_sec, fn):
        if remaining() < est_sec:
            note(f"{name}: skipped (est {est_sec:.0f}s > "
                 f"{remaining():.0f}s left)")
            if name in window_lines:
                emit(_relay_line(*window_lines[name],
                                 reason=f"this run's budget ran out "
                                        f"({remaining():.0f}s left)"))
                return
            emit({"metric": name, "value": None, "unit": "",
                  "vs_baseline": None,
                  "skipped": f"budget ({remaining():.0f}s left)"})
            return
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            emit({"metric": name, "value": None, "unit": "",
                  "vs_baseline": None,
                  "error": f"{type(e).__name__}: {e}"[:400]})

    def kernel_ab():
        import jax

        if jax.default_backend() != "tpu":
            # Interpreter-mode timings would measure the interpreter,
            # not the kernel — a misleading "speedup". Chip-gated.
            emit({"metric": "packed_matmul_vs_xla_dense", "value": None,
                  "unit": "x", "vs_baseline": None,
                  "skipped": f"backend is {jax.default_backend()}; the "
                             f"kernel A/B is only meaningful on the MXU"})
            return
        ab = _bench_kernel_ab(HIDDEN)
        note(f"kernel A/B: packed {ab['packed_ms']}ms vs dense "
             f"{ab['dense_ms']}ms ({ab['speedup']}x)")
        emit({"metric": "packed_matmul_vs_xla_dense", "value": ab["speedup"],
              "unit": "x", "vs_baseline": None, **ab})

    def breakdown():
        # Off-TPU the Pallas pieces run in interpreter mode: the extended
        # per-term attribution (fused eval, superstep, kernel tiles) is
        # CPU-measurable — XLA:CPU proof between chip windows.
        import jax

        bd = _bench_epoch_breakdown(paths, labels, HIDDEN, sec_per_epoch,
                                    interpret=jax.default_backend() != "tpu")
        note(f"epoch breakdown: {bd}")
        emit({"metric": "cbow_epoch_breakdown", "value": bd["epoch_ms"],
              "unit": "ms", "vs_baseline": None, **bd})

    # Control/config2 runs measure one chunk past the transfer-absorbing
    # first chunk (the steady-state filter needs epochs beyond DEFAULT_CHUNK).
    from g2vec_tpu.train.trainer import DEFAULT_CHUNK
    control_epochs = DEFAULT_CHUNK + max(32, DEFAULT_CHUNK // 2)

    def xla_control():
        sec_d, mfu_d = _bench_train(paths, labels, HIDDEN,
                                    control_epochs, use_pallas=False)
        note(f"xla-dense control: sec/epoch={sec_d:.4f} mfu={mfu_d:.4f}")
        emit({"metric": "cbow_train_xla_dense_sec_per_epoch", "value":
              round(sec_d, 5), "unit": "s", "vs_baseline": None,
              "mfu": round(mfu_d, 4),
              "pallas_speedup": round(sec_d / sec_per_epoch, 2)})

    def config2_train():
        sec2, mfu2 = _bench_train(paths, labels, 512, control_epochs)
        tp = int(N_PATHS * (1 - VAL_FRACTION))
        note(f"config2 train (hidden=512): sec/epoch={sec2:.4f} mfu={mfu2:.4f}")
        emit({"metric": "config2_train_paths_per_sec_per_chip",
              "value": round(tp / sec2, 1), "unit": "paths/s",
              "vs_baseline": None, "hidden": 512,
              "sec_per_epoch": round(sec2, 5), "mfu": round(mfu2, 4)})

    def walker_restricted():
        # Apples-to-apples stage-3 shape (7,523 genes), both backends,
        # beside the full-network stress line above — with its own
        # reference-loop baseline on the SAME restricted graph, so
        # vs_baseline compares like with like (VERDICT item 8).
        import jax
        import jax.numpy as jnp

        from g2vec_tpu.ops.graph import neighbor_table
        from g2vec_tpu.ops.host_walker import edges_to_csr as _csr

        s_r, d_r, w_r, ng_r = _restrict_bench_edges(
            edges[0], edges[1], edges[2], n_genes)
        base_r, nb_r = _reference_walk_baseline(
            *_csr(s_r, d_r, w_r, ng_r), ng_r, LEN_PATH,
            budget_s=min(BASELINE_BUDGET, 8.0))
        idx_r, wt_r = neighbor_table(s_r, d_r, w_r, ng_r)
        table_r = (jax.device_put(jnp.asarray(idx_r, jnp.int32)),
                   jax.device_put(jnp.asarray(wt_r, jnp.float32)))
        res_r = _bench_walker(table_r, ng_r, LEN_PATH, WALKER_REPS)
        note(f"restricted walker ({ng_r} genes, {s_r.size} edges): "
             f"{res_r['walks_per_sec']:.0f} walks/s; reference loop "
             f"{base_r:.1f} walks/s ({nb_r} walks)")
        emit({"metric": "walker_restricted_walks_per_sec",
              "value": round(res_r["walks_per_sec"], 1), "unit": "walks/s",
              "vs_baseline": round(res_r["walks_per_sec"] / base_r, 2),
              "baseline_host_walks_per_sec": round(base_r, 2),
              "unique_paths": res_r["unique_paths"], "n_genes": ng_r,
              "n_edges": int(s_r.size), "len_path": LEN_PATH,
              "reps": WALKER_REPS, "walker_batch": res_r["batch"],
              "note": "stage-3 walk shape: bundled network restricted to "
                      "the transcript's 7,523-gene expression∩network set"})
        emit(_native_walker_line(
            s_r, d_r, w_r, ng_r, base_r, note,
            {"n_edges": int(s_r.size),
             "baseline_host_walks_per_sec": round(base_r, 2),
             "note": "native C++ sampler on the same restricted graph"},
            metric="walker_native_restricted_walks_per_sec",
            n_threads=_cli_sampler_threads()))

    def config2_walker():
        res2 = _bench_walker(table, n_genes, 160, WALKER_REPS)
        note(f"config2 walker (lenPath=160): {res2['walks_per_sec']:.0f} "
             f"walks/s")
        line2 = {"metric": "config2_walker_walks_per_sec",
                 "value": round(res2["walks_per_sec"], 1), "unit": "walks/s",
                 "vs_baseline": None, "len_path": 160,
                 "unique_paths": res2["unique_paths"], "n_genes": n_genes,
                 "walker_batch": res2["batch"]}
        if "fused_launch_error" in res2:
            line2["fused_launch_error"] = res2["fused_launch_error"]
        emit(line2)

    # ---- opportunistic TPU acceptance (VERDICT r2 #2) ----
    # If this process is on the real chip and the round has no
    # TPU_ACCEPTANCE.json yet (e.g. the tunnel was down for the whole
    # interactive session, as in round 3), produce it HERE: it outranks the
    # optional control stages and the artifact lands in the repo for the
    # end-of-round commit. The trainer chunk program is shared with the
    # headline stage (same shapes), so the extra cost is the acceptance
    # walker/kmeans compiles plus the run itself.
    def tpu_acceptance():
        import jax

        from tools.tpu_acceptance import _code_key, run_acceptance

        repo = os.path.dirname(os.path.abspath(__file__))
        out_path = os.path.join(repo, "TPU_ACCEPTANCE.json")
        if jax.default_backend() != "tpu":
            emit({"metric": "tpu_acceptance_acc_val", "value": None,
                  "unit": "", "vs_baseline": None,
                  "skipped": f"backend is {jax.default_backend()}, not tpu"})
            return
        if os.path.exists(out_path):
            # Fresh only if recorded against THIS code state (tree hashes
            # of the measured sources — the commit hash would self-
            # invalidate when the artifact itself lands); a stale artifact
            # from older code must not stand in for a re-run.
            try:
                recorded = json.load(open(out_path)).get("code_key")
            except ValueError:
                recorded = None
            if recorded and recorded == _code_key():
                # Carry the fresh artifact's acc_val so this record is
                # self-contained (falls back to the skip if unreadable).
                emit(_acceptance_relay_line(
                    skip_reason="already recorded at this code state"))
                return

        # Abort cleanly if the run outlives the remaining budget: later
        # stages still get their skip/error lines and the parent's kill
        # window is never hit mid-pipeline. Thread watchdog, not SIGALRM:
        # the r5 window died in exactly this stage when the kmeans compile
        # blocked on a dead tunnel and the alarm signal was deferred until
        # the (never-returning) native call came back. hard=True turns
        # that wedge into an honest early exit 124 — the parent relays the
        # lines that already printed and its retry window survives.
        from tools.watchdog import watchdog

        with watchdog(max(30, int(remaining() - 25)),
                      "acceptance run exceeded the stage budget",
                      grace=20, hard=True):
            art = run_acceptance(out_path)
        ref_acc = art["reference_transcript"]["acc_val"]
        emit({"metric": "tpu_acceptance_acc_val",
              "value": round(art["acc_val"], 4),
              "unit": "ACC[val]",
              "vs_baseline": round(art["acc_val"] / ref_acc, 3),
              "n_paths": art["n_paths"],
              "stage_seconds": art["stage_seconds"],
              # Overlap attribution: how the stage_seconds were achieved
              # (sampler pool width, background time hidden under
              # foreground stages) — the measured overlap win.
              "sampler_threads": art.get("sampler_threads"),
              "overlap_saved_s": art.get("overlap_saved_s"),
              "pipeline_wall_seconds": art["pipeline_wall_seconds"]})

    if os.environ.get("G2VEC_BENCH_SKIP_ACCEPT") == "1":
        # A dedicated watcher stage owns the TPU_ACCEPTANCE refresh this
        # run: spend the child budget on the control/config2 lines below
        # instead of re-entering the ~7-compile acceptance pipeline. (r5
        # window #1: the tunnel died inside one of those compiles; SIGALRM
        # can't interrupt a blocked native call, so the stage held the
        # child until the parent's hard kill and every later line was
        # lost.) If that stage already refreshed the artifact AT THIS
        # code state, carry its acc_val here so this bench record is
        # self-contained.
        emit(_acceptance_relay_line())
    else:
        guarded("tpu_acceptance_acc_val", 180, tpu_acceptance)
    # After the acceptance stage so a just-written TPU_ACCEPTANCE.json (with
    # its history record) is what the convergence metric reads.
    emit(_epochs_to_088_line())
    guarded("packed_matmul_vs_xla_dense", 60, kernel_ab)
    guarded("cbow_epoch_breakdown", 120, breakdown)
    guarded("cbow_train_xla_dense_sec_per_epoch", 60, xla_control)
    guarded("config2_train_paths_per_sec_per_chip", 70, config2_train)
    if walker_err is None:
        guarded("config2_walker_walks_per_sec", 80, config2_walker)
        guarded("walker_restricted_walks_per_sec", 80, walker_restricted)
    else:
        for m in ("config2_walker_walks_per_sec",
                  "walker_restricted_walks_per_sec"):
            emit({"metric": m, "value": None,
                  "unit": "walks/s", "vs_baseline": None,
                  "skipped": f"headline walker stage failed: "
                             f"{walker_err}"[:400]})
    # The driver records the LAST line as "the result" (BENCH_r0N.json
    # "parsed"), and the stated contract is the headline train metric —
    # restate it so a chip round's record leads with the right number
    # (stage order above is priority-under-budget and cannot end on it).
    emit({**headline, "restated": True})


if __name__ == "__main__":
    if "--_probe" in sys.argv:
        _probe()
    elif "--_measure" in sys.argv:
        _measure()
    elif "--_hostonly" in sys.argv:
        _hostonly()
    elif "--_batch_ab" in sys.argv:
        _batch_ab()
    elif "--_scenario_ab" in sys.argv:
        _scenario_ab()
    elif "--_serve_ab" in sys.argv:
        _serve_ab()
    elif "--_stream_ab" in sys.argv:
        _stream_ab()
    elif "--_router_chaos" in sys.argv:
        _router_chaos()
    elif "--_partition_chaos" in sys.argv:
        _partition_chaos()
    elif "--_autoscale_ab" in sys.argv:
        _autoscale_ab()
    elif "--_query_latency" in sys.argv:
        _query_latency()
    elif "--_ann_ab" in sys.argv:
        _ann_ab()
    elif "--_update_ab" in sys.argv:
        _update_ab()
    elif "--_chaos_soak" in sys.argv:
        _chaos_soak()
    elif "--_shard_scale" in sys.argv:
        _shard_scale()
    elif "--_edge_ab" in sys.argv:
        _edge_ab()
    elif "--_device_walk" in sys.argv:
        _device_walk()
    else:
        main()
