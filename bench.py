"""Benchmark: training + walker throughput at the bundled-example scale.

Prints TWO JSON lines (the headline first), each
``{"metric", "value", "unit", "vs_baseline", ...}``:

1. ``cbow_train_paths_per_sec_per_chip`` — full-batch training of the
   two-matmul CBOW classifier on a 45,402 x 7,523 multi-hot path matrix,
   hidden=128. Each epoch is one fwd+bwd+Adam step over the whole 80% train
   split plus TWO full forward accuracy evals (val and train), exactly the
   reference's per-epoch work (ref: G2Vec.py:264-267). Baseline: the
   reference transcript's ~2.2 s/epoch steady state (README.md:36-40,
   BASELINE.md) with 36,321 train paths -> ~16.5k paths/s.
2. ``walker_walks_per_sec`` — stage 3, the reference's self-declared "most
   time consuming step" (ref: G2Vec.py:58): weighted no-revisit random walks
   (lenPath=80) from every gene of the REAL bundled network
   (``/root/reference/ex_NETWORK.txt``, 9.9k genes / 299k edges; synthetic
   scale-matched fallback when the mount is absent), sparse neighbor-table
   walker on device. Baseline: a bounded in-process run of the reference's
   own per-node Python/NumPy walk loop (deepcopy + np.random.choice per
   step, ref: G2Vec.py:328-346) on this host, extrapolated to walks/s — the
   reference publishes no walker timing, so its own algorithm on the bench
   machine is the fairest anchor.

Robustness (round-1 postmortem, VERDICT.md): the TPU tunnel can be down or
wedge indefinitely, and a raw crash/hang costs the round its only perf
artifact. So this script is a thin orchestrator that never imports jax
itself: it first PROBES the backend in a subprocess with a hard timeout
(retrying a flaky tunnel), then runs the measurement in a second bounded
subprocess. Every failure path prints a JSON-parseable error line and exits
nonzero within seconds of the deadline.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Reference transcript numbers (README.md:26-41, see BASELINE.md). The env
# overrides exist for smoke-testing the bench plumbing at toy scale (CI /
# CPU); driver runs use the defaults.
N_PATHS = int(os.environ.get("G2VEC_BENCH_N_PATHS", "45402"))
N_GENES = int(os.environ.get("G2VEC_BENCH_N_GENES", "7523"))
HIDDEN = int(os.environ.get("G2VEC_BENCH_HIDDEN", "128"))
VAL_FRACTION = 0.2
BASELINE_EPOCH_SECONDS = 2.2
BASELINE_PATHS_PER_SEC = int(N_PATHS * (1 - VAL_FRACTION)) / BASELINE_EPOCH_SECONDS

# Walker workload: every gene of the real network, reference CLI defaults.
LEN_PATH = int(os.environ.get("G2VEC_BENCH_LEN_PATH", "80"))
WALKER_REPS = int(os.environ.get("G2VEC_BENCH_WALKER_REPS", "10"))
REFERENCE_NETWORK = "/root/reference/ex_NETWORK.txt"

# The trainer runs epochs in device-resident chunks of DEFAULT_CHUNK (=64)
# epochs per dispatch; per-epoch times inside a chunk are uniform. The first
# measured chunk absorbs the host->device transfer of the (bit-packed) path
# matrix, so steady state is read from the chunks after it. A separate
# warmup call compiles the chunk program (the jit cache is shared across
# train_cbow calls).
WARMUP_EPOCHS = int(os.environ.get("G2VEC_BENCH_WARMUP_EPOCHS", "64"))
MEASURE_EPOCHS = int(os.environ.get("G2VEC_BENCH_MEASURE_EPOCHS", "192"))

PROBE_TIMEOUT = int(os.environ.get("G2VEC_BENCH_PROBE_TIMEOUT", "75"))
PROBE_ATTEMPTS = 3
MEASURE_TIMEOUT = int(os.environ.get("G2VEC_BENCH_TIMEOUT", "420"))
# Hard wall for the whole script: stay under the driver's ~560s kill so a
# wedge ALWAYS yields a JSON line, never an rc=124 with empty output.
TOTAL_BUDGET = int(os.environ.get("G2VEC_BENCH_TOTAL_BUDGET", "520"))

# Peak bf16 matmul throughput per chip, for the MFU estimate.
_PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}


def _as_text(data) -> str:
    """TimeoutExpired captures may be bytes or str depending on the runner."""
    if data is None:
        return ""
    return data.decode(errors="replace") if isinstance(data, bytes) else data


def _fail(stage: str, detail: str, code: int = 2) -> "NoReturn":  # noqa: F821
    print(json.dumps({
        "metric": "cbow_train_paths_per_sec_per_chip", "value": None,
        "unit": "paths/s", "vs_baseline": None,
        "error": f"{stage}: {detail}"[:500],
    }))
    sys.exit(code)


# --------------------------------------------------------------------------
# Parent orchestrator (no jax import in this process, ever).
# --------------------------------------------------------------------------

def main() -> None:
    deadline = time.time() + TOTAL_BUDGET
    last_err = "?"
    for attempt in range(PROBE_ATTEMPTS):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--_probe"],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {PROBE_TIMEOUT}s"
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            info = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"# backend probe ok: {info}", file=sys.stderr)
            break
        last_err = (proc.stderr or proc.stdout or "")[-300:]
        time.sleep(5)
    else:
        _fail("backend-probe", f"no usable jax backend after "
              f"{PROBE_ATTEMPTS} attempts: {last_err}")

    budget = max(60, min(MEASURE_TIMEOUT, int(deadline - time.time())))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_measure"],
            capture_output=True, text=True, timeout=budget)
        out, err, fail = proc.stdout or "", proc.stderr or "", (
            f"rc={proc.returncode}" if proc.returncode != 0 else None)
    except subprocess.TimeoutExpired as e:
        out, err = _as_text(e.stdout), _as_text(e.stderr)
        fail = f"measurement exceeded {budget}s"
    sys.stderr.write(err)
    # Relay whatever metric lines the child DID produce before dying — the
    # headline train line prints the moment it exists, so a walker-stage
    # wedge must not cost the round the training number.
    sys.stdout.write(out)
    if fail is not None:
        if out and not out.endswith("\n"):
            print()     # a killed child may leave a partial line behind
        if _has_real_metric(out):
            # Partial success: headline survived; record the stage failure
            # under a non-colliding metric name.
            print(json.dumps({"metric": "bench_stage_error", "value": None,
                              "unit": "", "vs_baseline": None,
                              "error": f"measure: {fail}: {err[-300:]}"[:500]}))
        else:
            _fail("measure", f"{fail}: {err[-300:]}")


def _has_real_metric(out: str) -> bool:
    """True iff a complete metric line with a non-null value was relayed."""
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("metric") and d.get("value") is not None:
                return True
    return False


def _probe() -> None:
    """Child: bounded backend initialization check."""
    import jax

    devs = jax.devices()
    print(json.dumps({"platform": jax.default_backend(),
                      "n_devices": len(devs),
                      "device0": str(devs[0])}))


# --------------------------------------------------------------------------
# Measurement child (runs only after the probe proved the backend alive).
# --------------------------------------------------------------------------

def make_paths(rng, n_paths: int, n_genes: int):
    """Multi-hot paths with planted good/poor gene blocks (~40 genes/path,
    matching the reference's mean path occupancy at lenPath=80)."""
    import numpy as np

    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    genes_per_path = 40
    idx = rng.integers(0, half, size=(n_paths, genes_per_path))
    idx += labels[:, None] * half
    np.put_along_axis(paths, idx, 1, axis=1)
    return paths, labels


def _bench_train() -> dict:
    import numpy as np

    from g2vec_tpu.train.trainer import DEFAULT_CHUNK, train_cbow

    rng = np.random.default_rng(0)
    paths, labels = make_paths(rng, N_PATHS, N_GENES)
    common = dict(hidden=HIDDEN, learning_rate=0.005,
                  val_fraction=VAL_FRACTION, compute_dtype="bfloat16", seed=0)

    # Warmup call: compiles the chunk program (one chunk's worth of epochs).
    train_cbow(paths, labels, max_epochs=WARMUP_EPOCHS, **common)

    res = train_cbow(paths, labels, max_epochs=MEASURE_EPOCHS, **common)

    epoch_secs = [h["secs"] for h in res.history]
    steady = epoch_secs[DEFAULT_CHUNK:]   # first chunk absorbs the transfer
    if not steady:           # early stop in the first chunk — use what we have
        steady = epoch_secs
    sec_per_epoch = float(np.median(steady))
    train_paths = int(N_PATHS * (1 - VAL_FRACTION))
    paths_per_sec = train_paths / sec_per_epoch

    # MFU: matmul FLOPs per epoch. fwd X@W_ih (2*M*G*H) + dW = X^T@dH
    # (2*M*G*H) on the train split, one eval fwd each on train and val;
    # the [_, H] @ [H, 1] output matmuls are negligible.
    m_tr, m_val = train_paths, N_PATHS - train_paths
    flops = 2 * N_GENES * HIDDEN * (3 * m_tr + m_val)
    peak = _PEAK_FLOPS.get(os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"), 197e12)
    mfu = flops / sec_per_epoch / peak

    print(f"# train: sec/epoch={sec_per_epoch:.4f} (baseline "
          f"{BASELINE_EPOCH_SECONDS}) epochs={len(epoch_secs)} "
          f"mfu={mfu:.4f}", file=sys.stderr)
    return {
        "metric": "cbow_train_paths_per_sec_per_chip",
        "value": round(paths_per_sec, 1),
        "unit": "paths/s",
        "vs_baseline": round(paths_per_sec / BASELINE_PATHS_PER_SEC, 2),
        "sec_per_epoch": round(sec_per_epoch, 5),
        "mfu": round(mfu, 4),
    }


def _load_bench_network():
    """(nbr_idx, nbr_w, n_genes): the real bundled network with synthetic
    |PCC| weights on a survivor subset, or a scale-matched fallback."""
    import numpy as np

    from g2vec_tpu.ops.graph import neighbor_table

    rng = np.random.default_rng(42)
    if os.path.exists(REFERENCE_NETWORK):
        src_names, dst_names = [], []
        with open(REFERENCE_NETWORK) as f:
            next(f)
            for line in f:
                parts = line.rstrip().split("\t")
                if len(parts) == 2:
                    src_names.append(parts[0])
                    dst_names.append(parts[1])
        genes = sorted(set(src_names) | set(dst_names))
        g2i = {g: i for i, g in enumerate(genes)}
        src = np.fromiter((g2i[g] for g in src_names), np.int32)
        dst = np.fromiter((g2i[g] for g in dst_names), np.int32)
        # The transcript reports 216,540 of 298,799 edges surviving the
        # |PCC| > 0.5 filter (README.md:28): keep the same fraction.
        keep = rng.random(src.size) < (216540 / 298799)
        src, dst = src[keep], dst[keep]
        n_genes = len(genes)
    else:
        # Fallback: same scale, power-law-ish out-degrees.
        n_genes, n_edges = 9904, 216540
        src = rng.choice(n_genes, size=n_edges,
                         p=_powerlaw_probs(np, n_genes))
        dst = rng.integers(0, n_genes, size=n_edges).astype(np.int32)
        src = src.astype(np.int32)
    w = rng.uniform(0.5001, 1.0, size=src.size).astype(np.float32)
    nbr_idx, nbr_w = neighbor_table(src, dst, w, n_genes)
    return nbr_idx, nbr_w, n_genes


def _powerlaw_probs(np, n):
    p = (1.0 / np.arange(1, n + 1)) ** 0.8
    return p / p.sum()


def _reference_walk_baseline(nbr_idx, nbr_w, n_genes: int,
                             budget_s: float = 8.0) -> float:
    """Walks/s of the reference's own algorithm on this host.

    A faithful re-creation of generate_randomPath's per-step work
    (ref: G2Vec.py:328-346): copy the current node's dense transition row,
    zero the visited entries, renormalize, np.random.choice. Run on a
    walker sample within a time budget and extrapolate.
    """
    import numpy as np

    # Dense rows are what the reference indexes (adjMat[currentNode]).
    dense_rows = {}

    def row(i):
        r = dense_rows.get(i)
        if r is None:
            r = np.zeros(n_genes, dtype=np.float64)
            mask = nbr_w[i] > 0
            r[nbr_idx[i][mask]] = nbr_w[i][mask]
            dense_rows[i] = r
        return r

    rng = np.random.default_rng(7)
    starts = rng.permutation(n_genes)
    t0 = time.time()
    done = 0
    for s in starts:
        path = [int(s)]
        current = int(s)
        for _ in range(LEN_PATH - 1):
            prob = row(current).copy()          # the reference's deepcopy
            prob[path] = 0.0
            total = prob.sum()
            if total <= 0.0:
                break
            current = int(rng.choice(n_genes, p=prob / total))
            path.append(current)
        done += 1
        if time.time() - t0 > budget_s and done >= 20:
            break
    return done / (time.time() - t0)


def _bench_walker() -> dict:
    import jax
    import numpy as np

    from g2vec_tpu.ops.walker import generate_path_set

    nbr_idx, nbr_w, n_genes = _load_bench_network()
    print(f"# walker network: {n_genes} genes, "
          f"{int((nbr_w > 0).sum())} edges, D={nbr_idx.shape[1]}",
          file=sys.stderr)

    key = jax.random.key(0)
    # Tables go to device HERE so the timed window measures the walk, not
    # the host->device upload (generate_path_set's device_put is a no-op on
    # already-committed arrays). Warmup compiles the walk program.
    import jax.numpy as jnp

    table = (jax.device_put(jnp.asarray(nbr_idx, jnp.int32)),
             jax.device_put(jnp.asarray(nbr_w, jnp.float32)))
    generate_path_set(table, key, len_path=LEN_PATH, reps=1)

    t0 = time.time()
    paths = generate_path_set(table, key,
                              len_path=LEN_PATH, reps=WALKER_REPS)
    elapsed = time.time() - t0
    walks = n_genes * WALKER_REPS
    walks_per_sec = walks / elapsed

    baseline = _reference_walk_baseline(nbr_idx, nbr_w, n_genes)
    print(f"# walker: {walks} walks in {elapsed:.2f}s -> "
          f"{walks_per_sec:.0f} walks/s; {len(paths)} unique paths; "
          f"host reference loop: {baseline:.1f} walks/s", file=sys.stderr)
    return {
        "metric": "walker_walks_per_sec",
        "value": round(walks_per_sec, 1),
        "unit": "walks/s",
        "vs_baseline": round(walks_per_sec / baseline, 2),
        "unique_paths": len(paths),
        "baseline_host_walks_per_sec": round(baseline, 2),
    }


def _measure() -> None:
    # The headline metric prints the moment it exists: a walker-stage crash
    # must never cost the round its training number.
    print(json.dumps(_bench_train()), flush=True)
    try:
        walker_line = _bench_walker()
    except Exception as e:  # noqa: BLE001 — degrade to an error line
        walker_line = {"metric": "walker_walks_per_sec", "value": None,
                       "unit": "walks/s", "vs_baseline": None,
                       "error": f"{type(e).__name__}: {e}"[:500]}
    print(json.dumps(walker_line), flush=True)


if __name__ == "__main__":
    if "--_probe" in sys.argv:
        _probe()
    elif "--_measure" in sys.argv:
        _measure()
    else:
        main()
