"""Benchmark: modified-CBOW training throughput at the bundled-example scale.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (matched to the reference's example transcript, README.md:26-41 and
BASELINE.md): full-batch training of the two-matmul CBOW classifier on a
45,402 x 7,523 multi-hot path matrix, hidden=128 — each epoch is one
fwd+bwd+Adam step over the whole 80% train split plus TWO full forward
accuracy evals (val and train), exactly the reference's per-epoch work
(ref: G2Vec.py:264-267).

Baseline: the reference's transcript reports ~2.2 s/epoch steady-state on
its (unstated) CPU with 36,321 train paths -> ~16.5k paths/s. vs_baseline
is our paths/s over that number.

The data is synthetic (the bundled expression matrix is stripped from the
mount — BASELINE.md note) with planted group structure so the accuracy
trajectory is non-trivial; throughput does not depend on the data values.
"""
from __future__ import annotations

import json
import time

import numpy as np

# Reference transcript numbers (README.md:26-41, see BASELINE.md).
N_PATHS = 45402
N_GENES = 7523
HIDDEN = 128
VAL_FRACTION = 0.2
BASELINE_EPOCH_SECONDS = 2.2
BASELINE_PATHS_PER_SEC = int(N_PATHS * (1 - VAL_FRACTION)) / BASELINE_EPOCH_SECONDS

# The trainer runs epochs in device-resident chunks of DEFAULT_CHUNK (=64)
# epochs per dispatch; per-epoch times inside a chunk are uniform. The first
# measured chunk absorbs the host->device transfer of the (bit-packed) path
# matrix, so steady state is read from the chunks after it. A separate
# warmup call compiles the chunk program (the jit cache is shared across
# train_cbow calls).
WARMUP_EPOCHS = 64
MEASURE_EPOCHS = 192


def make_paths(rng: np.random.Generator, n_paths: int, n_genes: int):
    """Multi-hot paths with planted good/poor gene blocks (~40 genes/path,
    matching the reference's mean path occupancy at lenPath=80)."""
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    genes_per_path = 40
    idx = rng.integers(0, half, size=(n_paths, genes_per_path))
    idx += labels[:, None] * half
    np.put_along_axis(paths, idx, 1, axis=1)
    return paths, labels


def main() -> None:
    from g2vec_tpu.train.trainer import train_cbow

    rng = np.random.default_rng(0)
    paths, labels = make_paths(rng, N_PATHS, N_GENES)
    common = dict(hidden=HIDDEN, learning_rate=0.005,
                  val_fraction=VAL_FRACTION, compute_dtype="bfloat16", seed=0)

    # Warmup call: compiles the chunk program (one chunk's worth of epochs).
    train_cbow(paths, labels, max_epochs=WARMUP_EPOCHS, **common)

    t0 = time.time()
    res = train_cbow(paths, labels, max_epochs=MEASURE_EPOCHS, **common)
    total = time.time() - t0

    from g2vec_tpu.train.trainer import DEFAULT_CHUNK

    epoch_secs = [h["secs"] for h in res.history]
    steady = epoch_secs[DEFAULT_CHUNK:]   # first chunk absorbs the transfer
    if not steady:           # early stop in the first chunk — use what we have
        steady = epoch_secs
    sec_per_epoch = float(np.median(steady))
    train_paths = int(N_PATHS * (1 - VAL_FRACTION))
    paths_per_sec = train_paths / sec_per_epoch

    print(json.dumps({
        "metric": "cbow_train_paths_per_sec_per_chip",
        "value": round(paths_per_sec, 1),
        "unit": "paths/s",
        "vs_baseline": round(paths_per_sec / BASELINE_PATHS_PER_SEC, 2),
    }))
    import sys
    print(f"# sec/epoch={sec_per_epoch:.4f} (baseline {BASELINE_EPOCH_SECONDS}) "
          f"epochs={len(epoch_secs)} total={total:.1f}s", file=sys.stderr)


if __name__ == "__main__":
    main()
